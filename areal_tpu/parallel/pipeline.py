"""Pipeline parallelism: GPipe microbatch schedule as a GSPMD program.

The TPU-native counterpart of the reference's pipeline engines
(realhf/impl/model/backend/pipe_runner.py:274-778 instruction schedules and
megatron PP, areal/engine/megatron_engine.py:846-925). Those hand-drive
send/recv pairs between stage processes; here the whole fill-drain schedule
is ONE jitted program:

- the stacked layer dim L is sharded over the ``pp`` mesh axis (each stage
  owns L/S contiguous layers — the pytree stays a single scan-friendly
  stack, no per-stage module lists);
- a ``jax.shard_map`` manual only over ``pp`` (dp/cp/tp stay auto, so the
  usual GSPMD tensor/data sharding applies *inside* each stage) runs the
  classic GPipe loop: ``M + S - 1`` steps of ``lax.scan``, each step
  computing this stage's layers on its current microbatch and
  ``ppermute``-ing activations to the next stage;
- embedding and the vocab head run OUTSIDE the pipeline region with the
  token dim sharded over ``(pp, dp, cp)`` — the pp axis acts as extra data
  parallelism there, so no stage redundantly computes the (large) head;
- backward is jax.grad through the scan + ppermute: AD reverses the
  schedule into the symmetric drain-fill backward pipeline automatically.

Bubble fraction is (S-1)/(M+S-1), the GPipe figure; feed M >= 2S
microbatches to keep it small. Per-stage activation memory is O(M) saved
stage inputs (with remat inside each stage step), the GPipe tradeoff.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.models.config import TransformerConfig
from areal_tpu.ops.attention import AttnSpec
from areal_tpu.parallel.mesh import AXIS_CP, AXIS_DP, AXIS_PP, AXIS_TP
from areal_tpu.utils import jax_compat


def pp_size(mesh: Mesh | None) -> int:
    return int(mesh.shape.get(AXIS_PP, 1)) if mesh is not None else 1


def check_pp_compatible(
    cfg: TransformerConfig, mesh: Mesh, vpp: int = 1
) -> None:
    if vpp < 1:
        raise ValueError(f"backend.vpp must be >= 1, got {vpp}")
    s = pp_size(mesh)
    if s <= 1:
        if vpp > 1:
            from areal_tpu.utils import logging

            logging.getLogger("pipeline").warning(
                "backend.vpp=%d has no effect without pipeline parallelism "
                "(pp=1); interleaving is a pp schedule", vpp
            )
        return
    if cfg.num_hidden_layers % (s * vpp) != 0:
        raise ValueError(
            f"pipeline parallelism needs num_hidden_layers "
            f"({cfg.num_hidden_layers}) divisible by pp*vpp ({s}*{vpp})"
        )
    # VLM rides the gpipe path: the vision tower + image splice run
    # outside the stage conveyor (forward_packed_pipelined), so no layer
    # of the tower needs a stage assignment. 1F1B still excludes VLM
    # (engine falls back to gpipe).


def stage_attn_spec(spec: AttnSpec | None, mesh: Mesh | None = None) -> AttnSpec | None:
    """Attention dispatch used INSIDE a pipeline stage.

    The stage body runs under a shard_map that is manual over pp and auto
    over dp/cp/tp. When dp/cp/tp have extent > 1, the engine-level sharded
    dispatch (ring over token axes, heads over tp) is kept and marked
    ``nested_manual={pp}``: the ring/ulysses wrappers then NEST their
    shard_map (manualizing only their own axes on the context abstract
    mesh), so the Pallas flash kernel stays live inside pipeline stages
    under pp x tp / pp x dp / pp x cp layouts instead of degrading to
    O(T^2) einsum attention.

    Only a spec that was already ``impl="xla"`` (e.g. non-dividing heads
    under tp — AttnSpec.for_mesh) stays on the einsum path, loudly.
    """
    import dataclasses

    if spec is None:
        return None
    inner = 1
    if mesh is not None:
        for a in (AXIS_DP, AXIS_CP, AXIS_TP):
            inner *= int(mesh.shape.get(a, 1))
    impl = spec.impl
    if inner == 1 and impl in ("auto", "pallas", "pallas_interpret"):
        # pure pipeline parallelism: plain local dispatch inside the stage
        return AttnSpec(impl=impl, mesh=None, block=spec.block)
    if inner > 1 and spec.is_sharded and impl != "xla":
        return dataclasses.replace(spec, nested_manual=frozenset({AXIS_PP}))
    if impl != "xla" and inner > 1:
        from areal_tpu.utils import logging

        logging.getLogger("pipeline").warning(
            "attention inside pipeline stages falls back to O(T^2) einsum "
            "(impl=%s, spec not sharded over dp/cp/tp: %s) — check "
            "AttnSpec.for_mesh head divisibility",
            impl, spec,
        )
    return AttnSpec(impl="xla" if inner > 1 else impl, mesh=None, block=spec.block)


def vstage_arrange(a, s: int, v: int, lc: int):
    """[L, ...] -> [S, V, Lc, ...]: element [i, j] = virtual stage j*S + i
    (the Megatron interleaved layout; with v == 1 a pure reshape). Stored
    contiguously pp-sharded, so the strided assignment costs one weight
    collective-permute per call (and its transpose in backward)."""
    a2 = a.reshape(v, s, lc, *a.shape[1:])
    return jnp.swapaxes(a2, 0, 1)


def vstage_unarrange(a):
    """Inverse of :func:`vstage_arrange`: [S, V, Lc, ...] -> [L, ...]."""
    return jnp.swapaxes(a, 0, 1).reshape(-1, *a.shape[3:])


def conveyor_decode(u, m: int, s: int, v: int):
    """Group-injection conveyor algebra shared by the interleaved
    schedules: microbatch ``g*S + r`` enters virtual stage 0 at tick
    ``g*V*S + r``, hops one virtual stage per tick, and every device runs
    exactly one chunk per tick (collision-free). Decodes unit ``u`` (ticks
    since a device's first possible work) to
    ``(microbatch, vchunk, in_range)``."""
    sv = s * v
    uc = jnp.clip(u, 0, m * v - 1)
    g = uc // sv
    w = uc % sv
    return g * s + w % s, w // s, (u >= 0) & (u < m * v)


def pipeline_hidden(
    params: dict,
    cfg: TransformerConfig,
    embeds: jnp.ndarray,  # [M, T, H] post-embedding microbatch stack
    positions: jnp.ndarray,  # [M, T]
    segment_ids: jnp.ndarray,  # [M, T]
    mesh: Mesh,
    attn_spec: AttnSpec | None = None,
    remat: bool = True,
    remat_policy: str = "nothing_saveable",
) -> jnp.ndarray:
    """Run the decoder stack as an S-stage GPipe pipeline.

    Returns pre-final-norm hidden states [M, T, H], replicated over pp.
    """
    from areal_tpu.models.lm import _REMAT_POLICIES, _block

    s = pp_size(mesh)
    m = embeds.shape[0]
    inner_spec = stage_attn_spec(attn_spec, mesh)

    def run_stage(layers_local, x, pos, seg):
        def body(carry, lp):
            return _block(cfg, lp, carry, pos, seg, inner_spec), None

        if remat:
            body = jax.checkpoint(body, policy=_REMAT_POLICIES[remat_policy])
        y, _ = jax_compat.scan(body, x, layers_local, unroll=True)
        return y

    def stage_fn(layers_local, emb, pos_all, seg_all):
        stage = jax_compat.axis_index(AXIS_PP)
        steps = m + s - 1
        buf = jnp.zeros_like(emb[0])

        def body(carry, t):
            # at step t this stage works on microbatch (t - stage); the
            # clip keeps indices in range during fill/drain (those
            # iterations compute garbage that is never collected)
            midx = jnp.clip(t - stage, 0, m - 1)
            x0 = jax.lax.dynamic_index_in_dim(emb, midx, 0, keepdims=False)
            x_in = jnp.where(stage == 0, x0, carry)
            pos = jax.lax.dynamic_index_in_dim(
                pos_all, midx, 0, keepdims=False
            )
            seg = jax.lax.dynamic_index_in_dim(
                seg_all, midx, 0, keepdims=False
            )
            y = run_stage(layers_local, x_in, pos, seg)
            nxt = jax_compat.ppermute(
                y, AXIS_PP, [(i, i + 1) for i in range(s - 1)]
            )
            return nxt, y

        _, ys = jax_compat.scan(body, buf, jnp.arange(steps), unroll=True)
        # microbatch mb exits the last stage at step mb + s - 1
        out = ys[s - 1 :]
        out = jnp.where(stage == s - 1, out, 0.0)
        if shard_out:
            # reduce-scatter hands each stage its own token slice in one
            # collective (half the wire traffic of psum + slice, no
            # transient full-size buffer), and the pp-sharded out_specs
            # spare XLA an "involuntary full rematerialization" reshard at
            # the head boundary
            return jax_compat.psum_scatter(
                out, AXIS_PP, scatter_dimension=1, tiled=True
            )
        return jax.lax.psum(out, AXIS_PP)

    t = embeds.shape[1]
    shard_out = t % s == 0
    return jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), P(), P(), P()),
        out_specs=P(None, AXIS_PP) if shard_out else P(),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(params["layers"], embeds, positions, segment_ids)


def pipeline_train_step_1f1b(
    params: dict,
    cfg: TransformerConfig,
    mbs: dict,  # stacked [M, T, ...] microbatch dict (input_ids, positions,
    #             segment_ids, loss_mask, ... — everything loss_fn reads)
    mesh: Mesh,
    token_loss_fn,  # TokenLossFn: .fn(logp [T], ent [T], mb_row) -> SUM loss,
    #                 .temperature, .needs_entropy (engine fused-loss twin)
    attn_spec: AttnSpec | None = None,
    remat: bool = True,
    remat_policy: str = "nothing_saveable",
    acc_dtype=jnp.float32,
    vpp: int = 1,
) -> tuple[jnp.ndarray, dict]:
    """One-forward-one-backward pipeline schedule: (losses [M], grads).

    The TPU-native 1F1B (reference: realhf static_schedule.py:1F1B +
    pipe_runner.py instruction schedules), composable with ``vpp`` virtual
    stages (the Megatron INTERLEAVED 1F1B,
    reference areal/api/alloc_mode.py:216-241). Unlike
    ``forward_packed_pipelined`` (GPipe + AD, which stores O(M) stage
    activations through the reverse scan), this HAND-ROLLS forward and
    backward into ONE ``lax.scan`` where every tick runs one chunk-forward
    AND one chunk-backward (steady state), so live activation memory is the
    O(S*V) ring buffer of chunk inputs — the whole point of 1F1B. Backward
    recomputes the chunk forward from its stored input (full remat inside
    ``jax.vjp``).

    Interleaved schedule: virtual stage ``vs = vchunk*S + stage`` (chunk
    ``vchunk`` of device ``stage``, layers ``[vs*Lc, (vs+1)*Lc)``).
    Microbatches inject in groups of S (microbatch ``g*S + r`` enters
    virtual stage 0 at tick ``g*V*S + r`` — the same collision-free
    group-injection conveyor as ``pipeline_hidden_interleaved``) and hop
    one virtual stage per tick over a full-ring ``ppermute`` (the wrap edge
    carries chunk transitions). The BACKWARD conveyor is the forward
    conveyor mirrored in both device and chunk index
    (``stage' = S-1-stage``, ``vchunk' = V-1-vchunk``) and offset by
    ``V*S`` ticks — so backward of a microbatch starts right after its
    forward drains, the bubble shrinks to ``(S-1)`` CHUNK-times at each
    end, and the same algebra guarantees each device runs at most one
    forward and one backward chunk per tick. Total ticks
    ``M*V + V*S + S - 1`` (``M + 2S - 1`` at vpp=1, the plain 1F1B count).

    Schedule (plain v=1 view — stage s, microbatch m): forward at tick
    ``m + s``, backward at ``m + 2S - 1 - s``; messages ride one fwd
    ppermute and one bwd ppermute per tick. The LM head + loss are NOT a
    serial last-stage epilogue: the tick a microbatch exits its last
    virtual stage, that block output is psum-broadcast and each device
    runs the head for its own 1/S token slice down to per-token
    (logp, entropy) — the [T, V] logits never leave a device — then the
    tiny [T, 2] vectors psum together and the token loss runs over the
    FULL stream (so losses that roll labels/masks internally stay exact;
    this is the chunked fused-LM-head-loss pattern with chunk == stage
    slice). Head FLOPs stay distributed over the pp group, like the GPipe
    path's out-of-pipeline token-parallel head. The embedding lookup folds
    into virtual stage 0 (its weight gradient accumulates via scatter-add
    on the carry), so no O(M) cotangent stack exists anywhere.

    Requires the fused-loss contract (``TokenLossFn`` — with
    ``is_value=True`` the head/loss section swaps the LM head\'s
    (logp, entropy) for per-token values, which is how critics ride this
    schedule). VLM engines use the GPipe path (the vision tower runs
    outside the conveyor there); LoRA rides this schedule via the engine\'s
    vjp-of-merge wrapper. T must divide S. With vpp>1, M is padded up to a
    multiple of S (padded lanes circulate but every loss/grad contribution
    is validity-gated, so they change nothing).
    """
    from areal_tpu.models.lm import (
        _REMAT_POLICIES,
        _block,
        _norm,
    )
    from areal_tpu.utils.functional import (
        gather_logprobs,
        gather_logprobs_entropy,
    )

    s = pp_size(mesh)
    v = int(vpp)
    sv = s * v  # virtual stages
    m_real, t = mbs["input_ids"].shape
    assert t % s == 0, (
        f"1f1b shards the head over pp: tokens {t} must divide pp {s}"
    )
    tl = t // s
    if cfg.num_hidden_layers % sv != 0:
        raise ValueError(
            f"interleaved 1f1b needs num_hidden_layers "
            f"({cfg.num_hidden_layers}) divisible by pp*vpp ({s}*{v})"
        )
    lc = cfg.num_hidden_layers // sv

    # group injection is collision-free only for M % S == 0 (vpp>1): pad
    # with lanes whose loss/grad contributions the validity gates drop
    m = -(-m_real // s) * s if v > 1 else m_real
    if m != m_real:
        pad = m - m_real
        mbs = jax.tree.map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
            ),
            mbs,
        )

    kk = 2 * s  # per-chunk stage-input ring slots (live range < 2SV ticks,
    #             <= 2S in-flight microbatches per chunk, distinct mod 2S)
    steps = m * v + sv + s - 1
    inner_spec = stage_attn_spec(attn_spec, mesh)

    is_value = bool(getattr(token_loss_fn, "is_value", False))
    if cfg.is_critic and not is_value:
        raise NotImplementedError(
            "1f1b critics need a value-head TokenLossFn (is_value=True); "
            "use pp_schedule=gpipe otherwise"
        )
    if cfg.is_vlm:
        raise NotImplementedError("1f1b with a vision tower: use gpipe")
    if is_value:
        tied = False
        head_w = params["value_head"]  # [H, 1]
    else:
        tied = "lm_head" not in params
        head_w = params["embed"].T if tied else params["lm_head"]
    norm_b = params.get("final_norm_b")
    # learned positions (gpt2 wpe): gate on the CONFIG like every other
    # forward path — a stray pos_embed leaf must not change semantics
    pos_embed_w = None
    if cfg.pos_embed_type == "learned":
        pos_embed_w = params["pos_embed"]

    layers_arr = jax.tree.map(
        lambda a: vstage_arrange(a, s, v, lc), params["layers"]
    )

    def run_stage(chunk_layers, x, pos, seg):  # chunk_layers: [Lc, ...]
        def body(carry, lp):
            return _block(cfg, lp, carry, pos, seg, inner_spec), None

        if remat:
            body = jax.checkpoint(body, policy=_REMAT_POLICIES[remat_policy])
        y, _ = jax_compat.scan(body, x, chunk_layers, unroll=True)
        return y

    def stage_fn(layers_local, ids_all, pos_all, seg_all, mbs_rep, head_w_l,
                 norm_w, norm_b_l, embed_w, pos_embed_l):
        stage = jax_compat.axis_index(AXIS_PP)
        is_first = stage == 0
        is_last = stage == s - 1
        lo = stage * tl  # this stage's head token slice
        h = cfg.hidden_size
        has_nb = norm_b_l is not None
        has_pos = pos_embed_l is not None

        def embed_rows(ids, pos):
            from areal_tpu.models.lm import _embed

            p_emb = {"embed": embed_w}
            if has_pos:
                p_emb["pos_embed"] = pos_embed_l
            return _embed(p_emb, cfg, ids, pos)

        def decode_unit(u):
            return conveyor_decode(u, m, s, v)

        def chunk_of(vc):
            return jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a[0], vc, 0, False),
                layers_local,
            )

        def tick(carry, tt):
            (fwd_msg, bwd_msg, xbuf, dybuf, loss_vec, g_lay, g_emb, g_nw,
             g_nb, g_hw, g_pos) = carry

            # ---- forward: chunk vf of this device, microbatch mf ----
            mf, vf, f_in = decode_unit(tt - stage)
            f_valid = f_in & (mf < m_real)
            mfc = jnp.clip(mf, 0, m - 1)
            ids_f = jax.lax.dynamic_index_in_dim(ids_all, mfc, 0, False)
            pos_f = jax.lax.dynamic_index_in_dim(pos_all, mfc, 0, False)
            seg_f = jax.lax.dynamic_index_in_dim(seg_all, mfc, 0, False)
            # virtual stage 0 injects a fresh microbatch; every other
            # (device, chunk) consumes the ring carry (garbage during
            # fill/drain rides through; its writes park in scratch)
            fresh = is_first & (vf == 0)
            x_in = jnp.where(fresh, embed_rows(ids_f, pos_f), fwd_msg)
            # invalid ticks park their write in the scratch slot KK
            slot = jnp.where(f_valid, mfc % kk, kk)
            xbuf = jax.lax.dynamic_update_slice(
                xbuf, x_in[None, None], (vf, slot, 0, 0)
            )
            y = run_stage(chunk_of(vf), x_in, pos_f, seg_f)

            # ---- head + loss the tick microbatch ml exits its LAST
            #      virtual stage (device S-1, chunk V-1): enter + SV - 1.
            #      decode(tt - (SV-1)) hits chunk 0 exactly at enters.
            #      token-sliced across ALL devices ----
            ml, vl, l_in = decode_unit(tt - (sv - 1))
            l_valid = l_in & (vl == 0) & (ml < m_real)
            mlc = jnp.clip(ml, 0, m - 1)
            y_last = jax.lax.psum(jnp.where(is_last, y, 0.0), AXIS_PP)
            y_sl = jax.lax.dynamic_slice_in_dim(y_last, lo, tl, 0)

            mb_row = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, mlc, 0, False),
                mbs_rep,
            )
            labels_full = jnp.roll(
                jax.lax.dynamic_index_in_dim(ids_all, mlc, 0, False), shift=-1
            )
            labels_sl = jax.lax.dynamic_slice_in_dim(labels_full, lo, tl, 0)

            # head for THIS device's token slice -> per-token (logp,
            # entropy) (or [value, 0] for critics) only — no [T, V] logits
            # ever cross devices; the token loss then runs over the
            # psum-assembled FULL [T] vectors with the FULL microbatch row,
            # so losses that roll labels/masks internally stay exact (the
            # chunked fused-LM-head-loss pattern, models/lm.forward_fused_
            # logp, with chunk == stage slice)
            def head_q(y_, nw, nb, hw):
                xn = _norm(cfg, y_, nw, nb)
                if is_value:
                    vals = (xn @ hw).astype(jnp.float32)[:, 0]  # [tl]
                    return jnp.stack([vals, jnp.zeros_like(vals)], -1)
                logits = (xn @ hw).astype(jnp.float32)
                if token_loss_fn.needs_entropy:
                    logp, ent = gather_logprobs_entropy(
                        logits, labels_sl, token_loss_fn.temperature
                    )
                else:
                    logp = gather_logprobs(
                        logits, labels_sl, token_loss_fn.temperature
                    )
                    ent = jnp.zeros_like(logp)
                return jnp.stack([logp, ent], -1)  # [tl, 2]

            if has_nb:
                q_sl, pullq = jax.vjp(
                    head_q, y_sl, norm_w, norm_b_l, head_w_l
                )
            else:
                q_sl, pullq = jax.vjp(
                    lambda y_, nw, hw: head_q(y_, nw, None, hw),
                    y_sl, norm_w, head_w_l,
                )
            q_full = jax.lax.psum(
                jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros((t, 2), jnp.float32), q_sl, lo, 0
                ),
                AXIS_PP,
            )

            def tok_loss(qf):
                return token_loss_fn.fn(qf[:, 0], qf[:, 1], mb_row)

            loss_part, pull_l = jax.vjp(tok_loss, q_full)
            dq_full = pull_l(jnp.float32(1.0))[0]
            dq_sl = jax.lax.dynamic_slice(dq_full, (lo, 0), (tl, 2))
            if has_nb:
                dy_sl, dnw, dnb, dhw = pullq(dq_sl)
            else:
                dy_sl, dnw, dhw = pullq(dq_sl)
                dnb = None
            zeros_t = jnp.zeros((t, h), jnp.float32)
            dy_full = jax.lax.psum(
                jax.lax.dynamic_update_slice_in_dim(
                    zeros_t, dy_sl.astype(jnp.float32), lo, 0
                ),
                AXIS_PP,
            )
            # every device computed the (cheap) full token loss redundantly;
            # count it once — the end-of-scan psum over pp restores the total
            loss_vec = loss_vec.at[mlc].add(
                jnp.where(l_valid & is_first, loss_part, 0.0)
            )
            g_nw = g_nw + jnp.where(l_valid, dnw.astype(acc_dtype), 0.0)
            if has_nb:
                g_nb = g_nb + jnp.where(l_valid, dnb.astype(acc_dtype), 0.0)
            g_hw = g_hw + jnp.where(l_valid, dhw.astype(acc_dtype), 0.0)
            dyslot = jnp.where(l_valid, mlc % 2, 2)
            dybuf = jax.lax.dynamic_update_index_in_dim(
                dybuf, dy_full.astype(y.dtype), dyslot, 0
            )

            # ---- backward: the mirror conveyor (device S-1-stage, chunk
            #      V-1-vchunk run the forward algebra), offset SV ticks ----
            mb_, vcm, b_in = decode_unit(tt - sv - (s - 1 - stage))
            vb = v - 1 - vcm  # this device's chunk being back-propagated
            b_valid = b_in & (mb_ < m_real)
            mbc = jnp.clip(mb_, 0, m - 1)
            ids_b = jax.lax.dynamic_index_in_dim(ids_all, mbc, 0, False)
            pos_b = jax.lax.dynamic_index_in_dim(pos_all, mbc, 0, False)
            seg_b = jax.lax.dynamic_index_in_dim(seg_all, mbc, 0, False)
            # the LAST virtual stage (device S-1, chunk V-1 <=> mirror
            # chunk 0) seeds from the head's dy; everyone else from the ring
            last_unit = is_last & (vcm == 0)
            dy_in = jnp.where(
                last_unit,
                jax.lax.dynamic_index_in_dim(dybuf, mbc % 2, 0, False),
                bwd_msg,
            )
            x_st = jax.lax.dynamic_slice(
                xbuf, (vb, mbc % kk, 0, 0), (1, 1, t, h)
            )[0, 0]
            _, pull2 = jax.vjp(
                lambda L, x: run_stage(L, x, pos_b, seg_b), chunk_of(vb), x_st
            )
            dlay, dx = pull2(dy_in)
            g_lay = jax.tree.map(
                lambda a, d: a.at[0, vb].add(
                    jnp.where(b_valid, d.astype(acc_dtype), 0.0)
                ),
                g_lay, dlay,
            )
            # virtual stage 0's dx is the embedding cotangent
            dx_rows = jnp.where(
                b_valid & is_first & (vb == 0), dx.astype(acc_dtype), 0.0
            )
            demb_rows = dx_rows
            if cfg.scale_embeddings:
                demb_rows = demb_rows * (cfg.hidden_size**0.5)
            g_emb = g_emb.at[ids_b].add(demb_rows)
            if has_pos:
                # pos embed adds AFTER the embedding scale, so its
                # cotangent is the unscaled dx
                g_pos = g_pos.at[pos_b].add(dx_rows)

            # ---- messages for the next tick (full ring: the wrap edges
            #      carry chunk transitions; with v=1 the wrapped message is
            #      never consumed, same as the old open-chain permute) ----
            fwd_nxt = jax_compat.ppermute(
                y, AXIS_PP, [(i, (i + 1) % s) for i in range(s)]
            )
            bwd_nxt = jax_compat.ppermute(
                dx, AXIS_PP, [(i, (i - 1) % s) for i in range(s)]
            )
            return (
                fwd_nxt, bwd_nxt, xbuf, dybuf, loss_vec, g_lay, g_emb,
                g_nw, g_nb, g_hw, g_pos,
            ), None

        xdtype = embed_w.dtype
        carry0 = (
            jnp.zeros((t, h), xdtype),
            jnp.zeros((t, h), xdtype),
            jnp.zeros((v, kk + 1, t, h), xdtype),
            jnp.zeros((3, t, h), xdtype),
            jnp.zeros((m,), jnp.float32),
            jax.tree.map(
                lambda a: jnp.zeros(a.shape, acc_dtype), layers_local
            ),
            jnp.zeros(embed_w.shape, acc_dtype),
            jnp.zeros(norm_w.shape, acc_dtype),
            jnp.zeros(norm_w.shape, acc_dtype),
            jnp.zeros(head_w_l.shape, acc_dtype),
            jnp.zeros(
                pos_embed_l.shape if has_pos else (1, 1), acc_dtype
            ),
        )
        (
            _, _, _, _, loss_vec, g_lay, g_emb, g_nw, g_nb, g_hw, g_pos
        ) = jax_compat.scan(tick, carry0, jnp.arange(steps))[0]
        # token-sliced / device-local accumulators -> global sums (g_lay
        # stays per-device: it matches the pp-sharded chunk stack)
        loss_vec = jax.lax.psum(loss_vec, AXIS_PP)
        g_emb = jax.lax.psum(g_emb, AXIS_PP)
        g_nw = jax.lax.psum(g_nw, AXIS_PP)
        g_nb = jax.lax.psum(g_nb, AXIS_PP)
        g_hw = jax.lax.psum(g_hw, AXIS_PP)
        g_pos = jax.lax.psum(g_pos, AXIS_PP)
        return loss_vec, g_lay, g_emb, g_nw, g_nb, g_hw, g_pos

    loss_vec, g_lay, g_emb, g_nw, g_nb, g_hw, g_pos = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(
            P(AXIS_PP), P(), P(), P(), P(), P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), P(AXIS_PP), P(), P(), P(), P(), P()),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(
        layers_arr, mbs["input_ids"], mbs["positions"],
        mbs["segment_ids"], mbs, head_w, params["final_norm"], norm_b,
        params["embed"], pos_embed_w,
    )

    grads = {
        "embed": g_emb,
        "layers": jax.tree.map(vstage_unarrange, g_lay),
        "final_norm": g_nw,
    }
    if norm_b is not None:
        grads["final_norm_b"] = g_nb
    if pos_embed_w is not None:
        grads["pos_embed"] = g_pos
    if is_value:
        grads["value_head"] = g_hw
    elif tied:
        grads["embed"] = grads["embed"] + g_hw.T
    else:
        grads["lm_head"] = g_hw
    return loss_vec[:m_real], grads


def _stage_ticks(s: int, stage, work, operands, collect_last: bool):
    """Run the sequential stage conveyor: S ticks; at tick t stage t
    applies ``work`` to its operands (idle stages pass through via
    lax.cond, skipping their weight reads), then the activation ppermutes
    forward. Returns (final operands, last stage's computed activation
    psum-broadcast to every stage if ``collect_last``)."""

    def tick(carry, t):
        x, rest = carry[0], carry[1:]

        def run(ops):
            return work(*ops)

        def idle(ops):
            return ops

        x, *rest = jax.lax.cond(stage == t, run, idle, (x, *rest))
        y_keep = None
        if collect_last:
            y_keep = jnp.where((stage == s - 1) & (t == s - 1), x, 0.0)
        x = jax_compat.ppermute(
            x, AXIS_PP, [(i, i + 1) for i in range(s - 1)]
        )
        return (x, *rest), y_keep

    carry, ys = jax_compat.scan(tick, operands, jnp.arange(s))
    y = None
    if collect_last:
        y = jax.lax.psum(jnp.sum(ys, 0), AXIS_PP)  # one tick contributed
    return carry, y


def prefill_stream_pp(
    params: dict,
    cfg: TransformerConfig,
    cache: dict,  # paged pool {k, v: [L, NB, BS, KH, D]}, L sharded over pp
    input_ids: jnp.ndarray,  # [T] packed ragged stream
    positions: jnp.ndarray,  # [T]
    segment_ids: jnp.ndarray,  # [T], pad = -1
    last_idx: jnp.ndarray,  # [N]
    token_blocks: jnp.ndarray,  # [T] physical block per token (trash = 0)
    token_offsets: jnp.ndarray,  # [T]
    mesh: Mesh,
    attn_spec: AttnSpec | None = None,
    positions3: jnp.ndarray | None = None,
    pixel_values: jnp.ndarray | None = None,  # [Nimg, S, S, 3] / [P, pd]
    image_grid_thw: tuple | None = None,  # qwen2_vl static grids
) -> tuple[jnp.ndarray, dict]:
    """Serving prefill with the layer stack sharded over pipeline stages
    (the pipelined-generation role of realhf pipe_runner.py:375-648): the
    packed stream passes through the S stages sequentially; each stage
    scatters its local layers' K/V into its slice of the paged pool.

    VLM prompts ride this path too: the vision tower + placeholder splice
    run OUTSIDE the stage ring (``embed_with_images``, GSPMD-auto over the
    whole mesh) — the same tower-outside-the-conveyor design as the
    training-side ``forward_packed_pipelined(pixel_values=...)`` — so only
    the already-spliced hidden stream enters the conveyor.

    Returns (last-token logits [N, V] fp32, updated pool).
    """
    from areal_tpu.models.lm import (
        _norm,
        _pool_write,
        _prefill_stream_layer,
        embed_with_images,
    )

    s = pp_size(mesh)
    rope_pos = positions3 if positions3 is not None else positions
    x0 = embed_with_images(
        params, cfg, input_ids, positions, pixel_values, image_grid_thw
    )
    inner_spec = stage_attn_spec(attn_spec, mesh)

    def stage_fn(layers_local, pool, x_in):
        stage = jax_compat.axis_index(AXIS_PP)

        def work(x, pl):
            def body(carry, layer_in):
                lp, pool_layer = layer_in
                out, k, v = _prefill_stream_layer(
                    cfg, lp, carry, rope_pos, segment_ids, inner_spec
                )
                idx = (token_blocks, token_offsets)
                pool_layer = _pool_write(pool_layer, "k", idx, k)
                pool_layer = _pool_write(pool_layer, "v", idx, v)
                return out, pool_layer

            y, pl = jax_compat.scan(body, x, (layers_local, pl))
            return y, pl

        (_, pl), y = _stage_ticks(
            s, stage, work, (x_in, pool), collect_last=True
        )
        return y, pl

    pool_specs = jax.tree.map(lambda _: P(AXIS_PP), dict(cache))
    y, new_cache = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), pool_specs, P()),
        out_specs=(P(), pool_specs),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(params["layers"], dict(cache), x0)
    return _final_norm_head(cfg, params, y[last_idx]), new_cache


def prefill_rotated_pp(
    params: dict,
    cfg: TransformerConfig,
    cache: dict,  # paged pool {k, v: [L, NB, BS, KH, D]}, L sharded over pp
    ids: jnp.ndarray,  # [S, T] S packed ragged streams (one per stage slot)
    positions: jnp.ndarray,  # [S, T]
    segment_ids: jnp.ndarray,  # [S, T], pad = -1
    last_idx: jnp.ndarray,  # [S, N] final-token stream index per prompt
    token_blocks: jnp.ndarray,  # [S, T] physical block per token (trash = 0)
    token_offsets: jnp.ndarray,  # [S, T]
    mesh: Mesh,
    attn_spec: AttnSpec | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Wavefront-rotated prefill: S independent packed streams ride the
    stage ring like GPipe microbatches (stream m enters stage 0 at tick m,
    stage i at tick t prefills stream t-i), so all S stages are busy in
    steady state — ~S/2 x the sequential conveyor's throughput for an
    admission burst, at 2S-1 ticks of one-stage work total. Each stage
    scatters its local layers' K/V into its slice of the paged pool;
    fill/drain ticks write to the trash block. The admission path splits a
    multi-prompt burst into S streams to feed this (engine._prefill_seqs).

    Returns (last-token logits [S, N, V] fp32, updated pool).
    """
    from areal_tpu.models.lm import (
        _embed,
        _norm,
        _pool_write,
        _prefill_stream_layer,
    )

    s = pp_size(mesh)
    assert ids.shape[0] == s, (ids.shape, s)
    t = ids.shape[1]
    n = last_idx.shape[1]
    h = cfg.hidden_size
    x0 = _embed(params, cfg, ids, positions)  # [S, T, H]
    inner_spec = stage_attn_spec(attn_spec, mesh)
    steps = 2 * s - 1

    def stage_fn(layers_local, pool, emb):
        stage = jax_compat.axis_index(AXIS_PP)

        def tick(carry, tt):
            msg, out, pl = carry
            m = tt - stage
            valid = (m >= 0) & (m < s)
            mc = jnp.clip(m, 0, s - 1)
            seg = jax.lax.dynamic_index_in_dim(segment_ids, mc, 0, False)
            blk = jax.lax.dynamic_index_in_dim(token_blocks, mc, 0, False)
            off = jax.lax.dynamic_index_in_dim(token_offsets, mc, 0, False)
            blk = jnp.where(valid, blk, 0)  # invalid ticks -> trash block
            x_in = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(emb, mc, 0, False),
                msg,
            )

            rope_pos = jax.lax.dynamic_index_in_dim(positions, mc, 0, False)

            def body(c, layer_in):
                lp, pool_layer = layer_in
                out_c, k, v = _prefill_stream_layer(
                    cfg, lp, c, rope_pos, seg, inner_spec
                )
                pool_layer = _pool_write(pool_layer, "k", (blk, off), k)
                pool_layer = _pool_write(pool_layer, "v", (blk, off), v)
                return out_c, pool_layer

            y, pl = jax_compat.scan(body, x_in, (layers_local, pl))
            is_out = (stage == s - 1) & valid
            li = jax.lax.dynamic_index_in_dim(last_idx, mc, 0, False)
            rows = y[li]  # [N, H]
            slot = jnp.where(is_out, mc, s)
            out = jax.lax.dynamic_update_index_in_dim(out, rows, slot, 0)
            nxt = jax_compat.ppermute(
                y, AXIS_PP, [(i, i + 1) for i in range(s - 1)]
            )
            return (nxt, out, pl), None

        carry0 = (
            jnp.zeros((t, h), emb.dtype),
            jnp.zeros((s + 1, n, h), emb.dtype),
            pool,
        )
        (_, out, pl), _ = jax_compat.scan(
            tick, carry0, jnp.arange(steps), unroll=True
        )
        out = jnp.where(stage == s - 1, out[:s], 0.0)
        return jax.lax.psum(out, AXIS_PP), pl

    pool_specs = jax.tree.map(lambda _: P(AXIS_PP), dict(cache))
    hidden, new_cache = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), pool_specs, P()),
        out_specs=(P(), pool_specs),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(params["layers"], dict(cache), x0)
    return _final_norm_head(cfg, params, hidden), new_cache


def _final_norm_head(cfg, params, hidden) -> jnp.ndarray:
    """Final norm + LM head (tied or not) -> fp32 logits; the shared tail
    of every pp serving forward."""
    from areal_tpu.models.lm import _norm

    hidden = _norm(
        cfg, hidden, params["final_norm"], params.get("final_norm_b")
    )
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return (hidden @ head).astype(jnp.float32)


def decode_step_paged_pp(
    params: dict,
    cfg: TransformerConfig,
    cache: dict,  # paged pool, L sharded over pp
    input_ids: jnp.ndarray,  # [B, Tq]
    cache_len: jnp.ndarray,  # [B]
    block_table: jnp.ndarray,  # [B, NBT]
    active: jnp.ndarray,  # [B] bool
    mesh: Mesh,
    attn_spec: AttnSpec | None = None,
    compute_logits: bool = True,
    pos_offset: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray | None, dict]:
    """Paged decode with layers sharded over pipeline stages: the [B, Tq]
    activation rides the sequential stage conveyor (per-token latency is S
    stage passes — the price of serving a model S× larger than one chip's
    reach); idle stages cond-skip, so HBM traffic per token stays one full
    model read spread across stages. models/lm.decode_step_paged is the
    single-stage twin.
    """
    from areal_tpu.models.lm import _decode_paged_layer, _embed, _norm

    s = pp_size(mesh)
    b, tq = input_ids.shape
    nbt = block_table.shape[1]
    bs = cache["k"].shape[2]
    write_pos = cache_len[:, None] + jnp.arange(tq)[None, :]
    rope_pos = write_pos
    if pos_offset is not None:
        rope_pos = rope_pos + pos_offset[:, None]
    x0 = _embed(params, cfg, input_ids, rope_pos)
    li = jnp.clip(write_pos // bs, 0, nbt - 1)
    phys = jnp.take_along_axis(block_table, li, axis=1)
    phys = jnp.where(active[:, None], jnp.maximum(phys, 0), 0)
    flat_phys = phys.reshape(-1)
    flat_off = (write_pos % bs).reshape(-1)
    gather_ids = jnp.maximum(block_table, 0)
    inner_spec = stage_attn_spec(attn_spec, mesh)

    def stage_fn(layers_local, pool, x_in):
        stage = jax_compat.axis_index(AXIS_PP)

        def work(x, pl):
            def body(carry, layer_in):
                lp, pool_layer = layer_in
                out, pool_layer = _decode_paged_layer(
                    cfg, lp, pool_layer, carry, rope_pos,
                    flat_phys, flat_off, gather_ids, cache_len + tq,
                    inner_spec,
                )
                return out, pool_layer

            y, pl = jax_compat.scan(body, x, (layers_local, pl))
            return y, pl

        (_, pl), y = _stage_ticks(
            s, stage, work, (x_in, pool), collect_last=True
        )
        return y, pl

    pool_specs = jax.tree.map(lambda _: P(AXIS_PP), dict(cache))
    y, cache = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), pool_specs, P()),
        out_specs=(P(), pool_specs),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(params["layers"], dict(cache), x0)
    if not compute_logits:
        return None, cache
    return _final_norm_head(cfg, params, y), cache


def pipeline_hidden_interleaved(
    params: dict,
    cfg: TransformerConfig,
    embeds: jnp.ndarray,  # [M, T, H] post-embedding microbatch stack
    positions: jnp.ndarray,  # [M, T]
    segment_ids: jnp.ndarray,  # [M, T]
    mesh: Mesh,
    vpp: int,
    attn_spec: AttnSpec | None = None,
    remat: bool = True,
    remat_policy: str = "nothing_saveable",
) -> jnp.ndarray:
    """Interleaved (virtual-stage) pipeline schedule: the Megatron
    ``virtual_pipeline_parallel_size`` capability
    (reference: areal/api/alloc_mode.py:216-241 vpp plumbing, Megatron
    interleaved 1F1B), re-derived for the GSPMD conveyor.

    Each of the S pp devices owns V=``vpp`` NON-contiguous layer chunks:
    virtual stage ``j`` (layers ``[j*Lc, (j+1)*Lc)``, ``Lc = L/(S*V)``)
    lives on device ``j % S``. A microbatch circulates the pp ring V times,
    one chunk per tick, over a single ring ``ppermute`` that includes the
    wrap edge ``(S-1, 0)``. Microbatches inject in groups of S (group g,
    slot r enters stage 0 at tick ``g*V*S + r``), which makes the conveyor
    collision-free: at every tick each device runs exactly one chunk.

    Total ticks = ``M*V + S - 1`` of one-chunk work vs GPipe's
    ``M + S - 1`` ticks of V-chunk work — same compute, but the fill/drain
    bubble shrinks from ``(S-1)`` stage-times to ``(S-1)`` CHUNK-times:
    bubble fraction (S-1)/(M*V + S - 1), the V-fold interleaved-schedule
    reduction. With vpp=1 the index algebra degenerates exactly to
    ``pipeline_hidden``'s GPipe schedule.

    Cost note: params["layers"] is stored contiguously pp-sharded; the
    strided virtual-stage assignment is produced by a reshape+transpose
    under a sharding constraint, i.e. one weight collective-permute per
    call (and its transpose in backward). Storing the interleaved layout
    natively would delete that traffic; measured first.

    M is padded up to a multiple of S internally (pad lanes compute
    garbage that is never collected). Backward is AD through the scan,
    like the GPipe path.
    """
    from areal_tpu.models.lm import _REMAT_POLICIES, _block

    s = pp_size(mesh)
    v = int(vpp)
    m0 = embeds.shape[0]
    t_len = embeds.shape[1]
    h = embeds.shape[2]
    if cfg.num_hidden_layers % (s * v) != 0:
        raise ValueError(
            f"interleaved pp needs num_hidden_layers "
            f"({cfg.num_hidden_layers}) divisible by pp*vpp ({s}*{v})"
        )
    lc = cfg.num_hidden_layers // (s * v)
    m = -(-m0 // s) * s  # group injection needs M % S == 0
    if m != m0:
        pad = m - m0
        embeds = jnp.concatenate(
            [embeds, jnp.zeros((pad, t_len, h), embeds.dtype)]
        )
        # positions may be [M, T] or [M, 3, T] (qwen2_vl M-RoPE streams)
        positions = jnp.concatenate(
            [positions,
             jnp.zeros((pad,) + positions.shape[1:], positions.dtype)]
        )
        segment_ids = jnp.concatenate(
            [segment_ids, jnp.zeros((pad, t_len), segment_ids.dtype)]
        )
    steps = m * v + s - 1
    inner_spec = stage_attn_spec(attn_spec, mesh)

    layers_arr = jax.tree.map(
        lambda a: vstage_arrange(a, s, v, lc), params["layers"]
    )

    def run_chunk(chunk_layers, x, pos, seg):
        def body(carry, lp):
            return _block(cfg, lp, carry, pos, seg, inner_spec), None

        if remat:
            body = jax.checkpoint(body, policy=_REMAT_POLICIES[remat_policy])
        y, _ = jax_compat.scan(body, x, chunk_layers, unroll=True)
        return y

    def stage_fn(layers_local, emb, pos_all, seg_all):
        # layers_local: [1, V, Lc, ...]
        stage = jax_compat.axis_index(AXIS_PP)

        def tick(carry, tt):
            x_carry, out = carry
            mb, vchunk, in_range = conveyor_decode(tt - stage, m, s, v)
            # stage 0 / chunk 0 injects a fresh microbatch; every other
            # (stage, chunk) consumes the ring carry (garbage during
            # fill/drain rides through and is never collected)
            fresh = (stage == 0) & (vchunk == 0)
            x0 = jax.lax.dynamic_index_in_dim(emb, mb, 0, False)
            x_in = jnp.where(fresh, x0, x_carry)
            pos = jax.lax.dynamic_index_in_dim(pos_all, mb, 0, False)
            seg = jax.lax.dynamic_index_in_dim(seg_all, mb, 0, False)
            chunk_layers = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a[0], vchunk, 0, False),
                layers_local,
            )
            y = run_chunk(chunk_layers, x_in, pos, seg)
            # microbatch mb exits its last virtual stage on device S-1 at
            # chunk V-1; park every other tick's write in scratch row M
            is_out = (stage == s - 1) & (vchunk == v - 1) & in_range
            slot = jnp.where(is_out, mb, m)
            out = jax.lax.dynamic_update_index_in_dim(out, y, slot, 0)
            nxt = jax_compat.ppermute(
                y, AXIS_PP, [(i, (i + 1) % s) for i in range(s)]
            )
            return (nxt, out), None

        carry0 = (
            jnp.zeros((t_len, h), emb.dtype),
            jnp.zeros((m + 1, t_len, h), emb.dtype),
        )
        (_, out), _ = jax_compat.scan(tick, carry0, jnp.arange(steps), unroll=True)
        out = jnp.where(stage == s - 1, out[:m], 0.0)
        if shard_out:
            # same reduce-scatter trade as pipeline_hidden: each stage keeps
            # its own token slice, halving wire traffic and handing the head
            # boundary an already-pp-sharded tensor
            return jax_compat.psum_scatter(
                out, AXIS_PP, scatter_dimension=1, tiled=True
            )
        return jax.lax.psum(out, AXIS_PP)

    shard_out = t_len % s == 0
    out = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), P(), P(), P()),
        out_specs=P(None, AXIS_PP) if shard_out else P(),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(layers_arr, embeds, positions, segment_ids)
    return out[:m0]


def decode_rotated_pp(
    params: dict,
    cfg: TransformerConfig,
    cache: dict,  # paged pool {k, v: [L, NB, BS, KH, D]}, L sharded over pp
    last_tokens: jnp.ndarray,  # [B] int32
    cache_len: jnp.ndarray,  # [B]
    block_table: jnp.ndarray,  # [B, NBT]
    active: jnp.ndarray,  # [B] bool
    mesh: Mesh,
    rng: jax.Array,
    temp: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    greedy: jnp.ndarray,  # [B]
    steps: int,
    attn_spec: AttnSpec | None = None,
    pos_offset: jnp.ndarray | None = None,  # [B] qwen2_vl M-RoPE deltas
) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Batch-group-rotated pipelined decode: S× the conveyor's throughput.

    ``decode_step_paged_pp`` moves ONE batch through the S stages
    sequentially — S-1 stages idle every tick. Here the batch splits into
    S contiguous row groups that rotate through the ring: at tick t stage
    i decodes group ``(t - i) mod S`` (token ``(t - i) // S``), so in
    steady state EVERY stage is busy with a different group every tick.
    Group g's token k exits stage S-1 (head + on-device sampling) at tick
    ``g + k*S + S-1``; its embedded next token rides the wrap edge
    ``(S-1, 0)`` of the same ring ppermute that carries mid-stack
    activations, entering stage 0 exactly one tick later — a seamless
    software pipeline with no draining between tokens. Total ticks
    ``steps*S + S - 1`` of 1/S-batch work vs the conveyor's ``steps*S``
    ticks of full-batch work on one stage.

    The serving role of Megatron's pipelined generation
    (realhf/impl/model/backend/pipe_runner.py:375-648), shaped for one
    jitted lax.scan. Needs B % S == 0 (the engine rounds max_batch_size up
    to a multiple of pp at init so this always holds).

    Returns (tokens [steps, B], logprobs [steps, B], cache) — identical
    semantics to the engine's per-step scan over ``decode_step_paged``.
    """
    from areal_tpu.inference.sampling import sample_tokens
    from areal_tpu.models.lm import _decode_paged_layer, _embed, _norm

    s = pp_size(mesh)
    b = last_tokens.shape[0]
    assert b % s == 0, f"rotation needs batch {b} divisible by pp {s}"
    g_sz = b // s
    nbt = block_table.shape[1]
    ticks = steps * s + s - 1
    inner_spec = stage_attn_spec(attn_spec, mesh)
    h = cfg.hidden_size
    head_w = params.get("lm_head")
    if head_w is None:
        head_w = params["embed"].T
    norm_b = params.get("final_norm_b")
    rngs = jax.random.split(rng, ticks)

    def stage_fn(layers_local, pool):
        stage = jax_compat.axis_index(AXIS_PP)
        is_exit = stage == s - 1

        def tick(carry, xs):
            msg, toks_all, clen_all, pl = carry
            tt, rng_t = xs
            u = tt - stage
            uc = jnp.clip(u, 0, steps * s - 1)
            g = uc % s
            k = uc // s
            lo = g * g_sz

            tbl_g = jax.lax.dynamic_slice(block_table, (lo, 0), (g_sz, nbt))
            act_g = jax.lax.dynamic_slice(active, (lo,), (g_sz,))
            clen_g = jax.lax.dynamic_slice(clen_all, (lo,), (g_sz,))
            toks_g = jax.lax.dynamic_slice(toks_all, (lo,), (g_sz,))

            write_pos = clen_g[:, None]  # [G, 1]
            rope_pos = write_pos
            if pos_offset is not None:
                off_g = jax.lax.dynamic_slice(pos_offset, (lo,), (g_sz,))
                rope_pos = rope_pos + off_g[:, None]
            li = jnp.clip(write_pos // bs_, 0, nbt - 1)
            phys = jnp.take_along_axis(tbl_g, li, axis=1)
            # fill/drain ticks clip u to REAL (group, token) coordinates —
            # their garbage compute must land in the trash block (0), not
            # over the live row a valid tick already wrote. Validity MUST
            # come from the UNCLIPPED u (the clipped k always reads as
            # in-range)
            tick_valid = (u >= 0) & (u < steps * s)
            phys = jnp.where(
                tick_valid & act_g[:, None], jnp.maximum(phys, 0), 0
            )
            gather_ids = jnp.maximum(tbl_g, 0)

            # stage 0 / token 0 embeds the group's initial token; every
            # other (stage, token) consumes the ring carry (for stage 0,
            # k>0 that carry IS the freshly sampled token's embedding,
            # placed there by the exit stage last tick)
            emb0 = _embed(params, cfg, toks_g[:, None], rope_pos)
            x_in = jnp.where((stage == 0) & (k == 0), emb0, msg)

            def body(c, layer_in):
                lp, pool_layer = layer_in
                out, pool_layer = _decode_paged_layer(
                    cfg, lp, pool_layer, c, rope_pos,
                    phys.reshape(-1), (write_pos % bs_).reshape(-1),
                    gather_ids, clen_g + 1, inner_spec,
                )
                return out, pool_layer

            y, pl = jax_compat.scan(body, x_in, (layers_local, pl))

            def exit_fn(y_):
                xn = _norm(cfg, y_[:, 0], params["final_norm"], norm_b)
                logits = (xn @ head_w).astype(jnp.float32)
                nxt, logp = sample_tokens(
                    logits,
                    rng_t,
                    jax.lax.dynamic_slice(temp, (lo,), (g_sz,)),
                    jax.lax.dynamic_slice(top_k, (lo,), (g_sz,)),
                    jax.lax.dynamic_slice(top_p, (lo,), (g_sz,)),
                    jax.lax.dynamic_slice(greedy, (lo,), (g_sz,)),
                )
                nxt = jnp.where(act_g, nxt, toks_g)
                emb_nxt = _embed(params, cfg, nxt[:, None], rope_pos + 1)
                return nxt, logp, emb_nxt.astype(y_.dtype)

            def skip_fn(y_):
                return (
                    jnp.zeros((g_sz,), jnp.int32),
                    jnp.zeros((g_sz,), jnp.float32),
                    jnp.zeros_like(y_),
                )

            exit_valid = is_exit & tick_valid
            nxt, logp, emb_nxt = jax.lax.cond(exit_valid, exit_fn, skip_fn, y)

            # replicated token/len state advances via exit-stage deltas
            zeros_b_i = jnp.zeros((b,), jnp.int32)
            tok_delta = jax.lax.dynamic_update_slice(
                zeros_b_i, jnp.where(exit_valid, nxt - toks_g, 0), (lo,)
            )
            len_delta = jax.lax.dynamic_update_slice(
                zeros_b_i,
                jnp.where(exit_valid, act_g.astype(jnp.int32), 0),
                (lo,),
            )
            toks_all = toks_all + jax.lax.psum(tok_delta, AXIS_PP)
            clen_all = clen_all + jax.lax.psum(len_delta, AXIS_PP)

            out_msg = jnp.where(exit_valid, emb_nxt, y)
            out_msg = jax_compat.ppermute(
                out_msg, AXIS_PP, [(i, (i + 1) % s) for i in range(s)]
            )
            ys_tok = jax.lax.psum(jnp.where(exit_valid, nxt, 0), AXIS_PP)
            ys_logp = jax.lax.psum(
                jnp.where(exit_valid, logp, 0.0), AXIS_PP
            )
            return (out_msg, toks_all, clen_all, pl), (ys_tok, ys_logp)

        bs_ = pool["k"].shape[2]
        carry0 = (
            # ring messages carry activations — embed dtype, not pool dtype
            jnp.zeros((g_sz, 1, h), params["embed"].dtype),
            last_tokens,
            cache_len,
            pool,
        )
        (_, _, _, pl), (toks, logps) = jax_compat.scan(
            tick, carry0, (jnp.arange(ticks), rngs)
        )
        return toks, logps, pl

    pool_specs = jax.tree.map(lambda _: P(AXIS_PP), dict(cache))
    toks_t, logps_t, new_cache = jax_compat.shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(AXIS_PP), pool_specs),
        out_specs=(P(), P(), pool_specs),
        axis_names=frozenset({AXIS_PP}),
        check_vma=False,
    )(params["layers"], dict(cache))

    # de-interleave ticks: group g's token k surfaced at tick g + k*S + S-1
    idx = (s - 1) + jnp.arange(s)[None, :] + jnp.arange(steps)[:, None] * s
    toks = toks_t[idx].reshape(steps, b)
    logps = logps_t[idx].reshape(steps, b)
    return toks, logps, new_cache


def forward_packed_pipelined(
    params: dict,
    cfg: TransformerConfig,
    input_ids: jnp.ndarray,  # [M, T] int32 microbatch stack
    positions: jnp.ndarray,  # [M, T] ([M, 3, T] for qwen2_vl M-RoPE)
    segment_ids: jnp.ndarray,  # [M, T]
    mesh: Mesh,
    attn_spec: AttnSpec | None = None,
    remat: bool = False,
    remat_policy: str = "nothing_saveable",
    vpp: int = 1,
    pixel_values: jnp.ndarray | None = None,  # [M, Pmax, pd] / [M, N, S, S, 3]
    image_grid_thw: tuple | None = None,  # static batch grid
) -> jnp.ndarray:
    """Pipelined counterpart of models/lm.forward_packed over M stacked
    microbatches: logits [M, T, V] fp32 (values [M, T] for critics).

    Embedding and head are computed outside the pipeline with the token dim
    sharded over (pp, dp, cp) — every device works on head FLOPs, none
    duplicates them.
    """
    from areal_tpu.models.lm import _embed, _norm, embed_with_images

    if pixel_values is not None:
        # vision tower + placeholder splice run OUTSIDE the pipeline, per
        # microbatch (vmapped over M): every pp device computes the (small)
        # tower, then only [M, T, H] embeddings enter the stage conveyor.
        # Stacked pixel tables are padded with ghost rows to a common Pmax;
        # ghost rows encode garbage that placeholder-rank gathering never
        # reads (models/lm.embed_with_images).
        x = jax.vmap(
            lambda ids, pos, px: embed_with_images(
                params, cfg, ids, pos, px, image_grid_thw
            )
        )(input_ids, positions, pixel_values)
    else:
        x = _embed(params, cfg, input_ids, positions)  # [M, T, H]
    hidden_fn = (
        partial(pipeline_hidden_interleaved, vpp=vpp)
        if vpp > 1
        else pipeline_hidden
    )
    x = hidden_fn(
        params,
        cfg,
        x,
        positions,
        segment_ids,
        mesh,
        attn_spec=attn_spec,
        remat=remat,
        remat_policy=remat_policy,
    )
    # spread head/loss work across ALL devices: pp joins dp/cp as token
    # parallelism for the out-of-pipeline ops
    x = jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, (AXIS_PP, AXIS_DP, AXIS_CP), None))
    )
    x = _norm(cfg, x, params["final_norm"], params.get("final_norm_b"))
    if cfg.is_critic:
        return (x @ params["value_head"]).astype(jnp.float32)[..., 0]
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return (x @ head).astype(jnp.float32)
