"""Parameter / activation sharding rules (GSPMD PartitionSpecs).

The TPU-native replacement for the reference's three TP implementations
(DTensor plans, Megatron-core TP, hand-written Column/RowParallelLinear —
SURVEY §2.2): weights get ``NamedSharding`` annotations and XLA inserts the
all-reduces/all-gathers that Megatron hand-codes.

Rules for the stacked-leaf decoder pytree (leaf shapes include the leading
layer dim L, which is scanned over and never sharded):

- wq/wk/wv [L,H,heads*D]  -> tp shards the head (output) dim; fsdp shards H
- wo       [L,heads*D,H]  -> tp shards the head (input) dim  (row-parallel)
- wg/wu    [L,H,I]        -> tp shards I
- wd       [L,I,H]        -> tp shards I (row-parallel)
- embed    [V,H]          -> tp shards V (vocab-parallel embedding + logits)
- lm_head  [H,V]          -> tp shards V
- biases/norms            -> replicated (fsdp-sharded if large)
- MoE router [L,H,E]      -> replicated over tp
- MoE wg/wu [L,E,H,I]     -> ep shards E (expert parallel: the folded
  (dp,cp) axes), tp shards I
- value_head [H,1]        -> replicated

FSDP (ZeRO-3-style) additionally shards each weight's largest non-tp dim over
the ("dp","cp") axes; under jit XLA all-gathers just-in-time per layer of the
scan, which is exactly FSDP's prefetch behavior, and the optimizer state
inherits the sharding so it is ZeRO-sharded too.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.parallel.mesh import AXIS_CP, AXIS_DP, AXIS_PP, AXIS_TP

FSDP_AXES = (AXIS_DP, AXIS_CP)  # combined data axes used for param sharding
EP_AXES = (AXIS_DP, AXIS_CP)  # expert axis = folded data axes (MoE folding)


def param_spec(path: tuple, leaf: Any, fsdp: bool, pp: bool = False) -> P:
    """PartitionSpec for one stacked-leaf param, keyed by its pytree path.

    ``pp=True`` (mesh has a real pipeline axis) shards the stacked layer
    dim L of every in-layers leaf over ``pp`` — each pipeline stage owns
    its contiguous L/pp layer slice at rest, matching the shard_map
    in_specs of parallel/pipeline.py so entering the pipeline moves no
    weights."""
    keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
    name = keys[-1]
    in_layers = "layers" in keys
    if pp and in_layers:
        base = tuple(param_spec(path, leaf, fsdp, pp=False))
        return (AXIS_PP,) + base[1:]

    def fs(axis_spec):
        """Optionally add fsdp sharding on the first shardable None dim.

        Layer-stacked leaves never shard dim 0 (the scanned L dim); top-level
        leaves (embed/lm_head) may shard any dim."""
        if not fsdp:
            return axis_spec
        spec = list(axis_spec)
        first = 1 if in_layers else 0
        for i, s in enumerate(spec):
            if s is None and i >= first:
                spec[i] = FSDP_AXES
                return tuple(spec)
        return tuple(spec)

    if not in_layers:
        if name == "embed":
            return fs((AXIS_TP, None))
        if name == "lm_head":
            return fs((None, AXIS_TP))
        if name == "value_head":
            return P(None, None)
        if name == "final_norm":
            return P(None)
        return P()

    # layer-stacked leaves: dim 0 is L
    if name in ("wq", "wk", "wv"):
        return fs((None, None, AXIS_TP))
    if name == "wo":
        return fs((None, AXIS_TP, None))
    if name == "router":
        return fs((None, None, None))
    if name in ("wg", "wu"):
        if leaf is not None and getattr(leaf, "ndim", 3) == 4:  # MoE [L,E,H,I]
            return (None, EP_AXES, None, AXIS_TP)
        return fs((None, None, AXIS_TP))
    if name == "wd":
        if leaf is not None and getattr(leaf, "ndim", 3) == 4:  # MoE [L,E,I,H]
            return (None, EP_AXES, AXIS_TP, None)
        return fs((None, AXIS_TP, None))
    if name in ("bq", "bk", "bv"):
        return P(None, AXIS_TP)
    # norms and other small per-layer vectors
    return P(None, None) if getattr(leaf, "ndim", 1) >= 2 else P(None)


def param_shardings(mesh: Mesh, params_shape_tree: Any, fsdp: bool = True):
    """Pytree of NamedShardings matching ``params_shape_tree``.

    Dims that don't divide evenly by their assigned axes fall back to
    replication on that dim (GSPMD requires even sharding for inputs placed
    via device_put; XLA can still re-shard internally)."""

    pp = mesh.shape.get(AXIS_PP, 1) > 1

    def build(path, leaf):
        spec = param_spec(path, leaf, fsdp, pp=pp)
        spec = _evenly_divisible(mesh, spec, getattr(leaf, "shape", ()))
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(build, params_shape_tree)


def _axis_size(mesh: Mesh, axis) -> int:
    if axis is None:
        return 1
    if isinstance(axis, (tuple, list)):
        out = 1
        for a in axis:
            out *= mesh.shape[a]
        return out
    return mesh.shape[axis]


def _evenly_divisible(mesh: Mesh, spec, shape) -> tuple:
    out = []
    for i, axis in enumerate(spec):
        if axis is None or i >= len(shape):
            out.append(axis)
            continue
        if shape[i] % _axis_size(mesh, axis) != 0:
            out.append(None)
        else:
            out.append(axis)
    return tuple(out)


def logits_spec() -> P:
    """Activations: packed token dim sharded over (dp,cp); vocab over tp."""
    return P(FSDP_AXES, AXIS_TP)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
