"""Device mesh construction from a ParallelStrategy.

The TPU-native replacement for the reference's process-group zoo (FSDP
DeviceMesh at areal/utils/fsdp/parallel.py:155-179, Megatron parallel_state,
legacy ProcessTopology at realhf/base/topology.py): ONE ``jax.sharding.Mesh``
per job with named axes, and GSPMD inserts all collectives.

Axis order is ("pp", "dp", "cp", "tp") — fastest-varying last so TP groups
map onto adjacent devices (ICI neighbors on a TPU slice), CP next (ring over
ICI), then DP, then PP across the slowest links. The expert axis for MoE is
the folded ("dp","cp") pair reinterpreted as ("edp","ep") — same devices,
different logical view, matching the reference's MoE parallel folding
(SURVEY §2.2 EP row).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from areal_tpu.api.alloc_mode import ParallelStrategy

AXIS_PP = "pp"
AXIS_DP = "dp"
AXIS_CP = "cp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_PP, AXIS_DP, AXIS_CP, AXIS_TP)


def make_mesh(
    parallel: ParallelStrategy, devices: list | None = None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    need = parallel.world_size
    full_fold = parallel.dp * parallel.cp
    if parallel.ep > 1 and parallel.ep != full_fold:
        # expert weights shard over the WHOLE folded (dp, cp) extent
        # (parallel/sharding.py EP_AXES); a partial expert group would need
        # a factored mesh axis that is not built — reject instead of
        # silently sharding over a different group size than requested
        raise NotImplementedError(
            f"expert parallelism folds over ALL of (dp, cp) = {full_fold}; "
            f"partial ep={parallel.ep} is not implemented (write e{full_fold} "
            "or omit the e dim)"
        )
    if parallel.ep > 1 and parallel.etp != parallel.tp:
        raise NotImplementedError(
            f"expert weights always shard their I dim over tp={parallel.tp}; "
            f"etp={parallel.etp} is not implemented (write the ffn layout "
            f"with t{parallel.tp} or drop tensor parallelism)"
        )
    if len(devices) < need:
        raise ValueError(
            f"ParallelStrategy {parallel} needs {need} devices, "
            f"only {len(devices)} available"
        )
    n_procs = len({d.process_index for d in devices})
    if n_procs > 1 and need < len(devices):
        # jax.devices() is process-major: a plain [:need] slice can select
        # devices from a strict subset of processes, leaving other hosts
        # with no addressable shard (make_array_from_process_local_data
        # then dies with StopIteration). Take an equal share from every
        # process instead.
        if need % n_procs != 0:
            raise ValueError(
                f"{need} mesh devices cannot be split evenly over "
                f"{n_procs} processes"
            )
        per = need // n_procs
        by_proc: dict[int, list] = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        short = {p: len(ds) for p, ds in by_proc.items() if len(ds) < per}
        if short:
            raise ValueError(
                f"need {per} mesh devices from every process but "
                f"{short} have fewer"
            )
        devices = [
            d for p in sorted(by_proc) for d in by_proc[p][:per]
        ]
    else:
        devices = devices[:need]
    n_procs_used = len({d.process_index for d in devices})
    if (
        n_procs_used > 1
        and parallel.pp > 1
        and parallel.dp % n_procs_used == 0
    ):
        # dp-OUTER layout: the process boundary lands on the dp axis, so
        # each host's devices cover a distinct dp slice across ALL pipeline
        # stages — every host feeds only its own data shard (the
        # reference's normal Megatron dp x pp placement,
        # areal/api/alloc_mode.py:216-241) instead of replicating the
        # global batch. pp here spans in-host devices; tp stays
        # fastest-varying (ICI neighbors).
        arr = np.asarray(devices).reshape(
            parallel.dp, parallel.pp, parallel.cp, parallel.tp
        )
        return Mesh(arr.transpose(1, 0, 2, 3), MESH_AXES)
    arr = np.asarray(devices).reshape(
        parallel.pp, parallel.dp, parallel.cp, parallel.tp
    )
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device=None) -> Mesh:
    device = device if device is not None else jax.devices()[0]
    return Mesh(np.asarray([device]).reshape(1, 1, 1, 1), MESH_AXES)
