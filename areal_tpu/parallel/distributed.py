"""Multi-host runtime: jax.distributed wiring + host-local batch assembly.

The TPU-native replacement for the reference's multi-node story (torchrun +
NCCL process groups + DP-rank data redistribution,
areal/core/dist_rollout.py:43-93 and areal/utils/data.py:838-1006): one
``jax.distributed`` service connects N processes, ``jax.devices()`` becomes
the GLOBAL device list, and the single GSPMD mesh spans every host — XLA
routes collectives over ICI within a slice and DCN across slices.

What replaces the reference's machinery:
- ``initialize()``            <- torch.distributed.init_process_group
- ``shard_rows()``            <- per-DP-rank dataset sharding (StatefulDataLoader
                                 rank/world args)
- ``host_local_to_global()``  <- broadcast_tensor_container / redistribute:
                                 each host contributes its LOCAL token shard
                                 and jax assembles the global sharded array —
                                 no gather/scatter round trip through rank 0.
- ``sync_max()/sync_sum()``   <- the synced microbatch allocation
                                 (allocate_balanced_mbs_synced): hosts agree
                                 on bucket sizes / loss normalizers with one
                                 tiny allgather.

Constraint (documented, asserted): the mesh axis order ("pp","dp","cp","tp")
with default device ordering gives each process a contiguous block of the
flattened (dp, cp) token axes, so a host's local sequences land in its own
device shards.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("distributed")


_INITIALIZED = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> None:
    """Connect this process to the jax.distributed service.

    Args fall back to AREAL_COORDINATOR_ADDR / AREAL_NUM_PROCESSES /
    AREAL_PROCESS_ID env vars (set by the launcher), then to jax's own
    cluster auto-detection (TPU metadata server on Cloud TPU pods). No-op
    for single-process runs (nothing set, nothing detected).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "AREAL_COORDINATOR_ADDR"
    )
    if num_processes is None and "AREAL_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["AREAL_NUM_PROCESSES"])
    if process_id is None and "AREAL_PROCESS_ID" in os.environ:
        process_id = int(os.environ["AREAL_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return  # single process / rely on auto-detection at backend init
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _INITIALIZED = True
    logger.info(
        f"jax.distributed up: process {jax.process_index()}/"
        f"{jax.process_count()}, {len(jax.local_devices())} local / "
        f"{len(jax.devices())} global devices"
    )


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_main() -> bool:
    return jax.process_index() == 0


def shard_rows(rows, index: int | None = None, count: int | None = None):
    """Per-process dataset shard (the reference's per-DP-rank split).

    Shards are truncated to EQUAL length — hosts must agree on
    steps_per_epoch or the straggler deadlocks in the first collective the
    others never join."""
    index = jax.process_index() if index is None else index
    count = jax.process_count() if count is None else count
    if count == 1:
        return rows
    per = len(rows) // count
    return rows[index::count][:per]


def host_local_to_global(mesh, spec, arr: np.ndarray):
    """Assemble a globally-sharded array from per-host local shards.

    Each process passes its LOCAL slice (e.g. its own packed token stream);
    the result is one global jax.Array sharded by ``spec`` over ``mesh``
    whose dim-0 is the concatenation of the per-process slices in process
    order. Single-process: plain device_put.
    """
    sharding = jax.sharding.NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


def allgather_rows(arr: np.ndarray) -> np.ndarray:
    """Concatenate per-process row tables in PROCESS ORDER (uneven row
    counts allowed). The multi-host image-table primitive: the global packed
    stream is the process-order concat of host streams
    (host_local_to_global), so a process-order image table keeps
    placeholder ranks aligned (models/vlm.splice_image_embeds)."""
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    counts = multihost_utils.process_allgather(
        np.asarray([arr.shape[0]], np.int64), tiled=True
    )  # [P]
    m = int(counts.max())
    if m == 0:
        return arr
    if arr.shape[0] < m:  # pad to the common max so shapes agree
        pad = np.zeros((m - arr.shape[0],) + arr.shape[1:], arr.dtype)
        arr = np.concatenate([arr, pad], axis=0)
    full = multihost_utils.process_allgather(arr, tiled=True)  # [P*m, ...]
    segs = [
        full[i * m : i * m + int(c)] for i, c in enumerate(counts)
    ]
    return np.concatenate(segs, axis=0)


def sync_max(value: float) -> float:
    """Max of a host-local scalar across processes (bucket-size agreement)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return float(np.max(multihost_utils.process_allgather(np.float64(value))))


def sync_sum(value: float) -> float:
    """Sum of a host-local scalar across processes (loss normalizers)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    return float(np.sum(multihost_utils.process_allgather(np.float64(value))))


def sync_max_vector(values, length: int) -> np.ndarray:
    """Columnwise max of per-host int vectors (padded with 0 to ``length``) —
    one collective for all microbatch bucket sizes instead of one each."""
    padded = np.zeros(length, np.int64)
    padded[: len(values)] = values
    if jax.process_count() == 1:
        return padded
    from jax.experimental import multihost_utils

    return np.max(multihost_utils.process_allgather(padded), axis=0)


def broadcast_obj(obj):
    """Broadcast an arbitrary picklable object from process 0 to all.

    The cross-host rollout distribution primitive (the reference moves
    rollout batches between DP ranks with torch broadcast,
    areal/utils/data.py:838-1006; here: pickle -> two fixed-shape
    broadcast_one_to_all collectives, length then payload). Every process
    must call this in the same order; non-source processes pass obj=None.
    """
    if jax.process_count() == 1:
        return obj
    import pickle

    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        ln = np.array([payload.size], np.int64)
    else:
        payload = None
        ln = np.zeros(1, np.int64)
    ln = int(multihost_utils.broadcast_one_to_all(ln)[0])
    if payload is None:
        payload = np.zeros(ln, np.uint8)
    payload = multihost_utils.broadcast_one_to_all(payload)
    return pickle.loads(bytes(payload.tobytes()))


def gather_tree_for_main(tree):
    """Gather a cross-host-sharded pytree to host RAM on process 0 ONLY,
    leaf by leaf: every host joins each per-leaf collective, but non-main
    hosts discard the result immediately, so their peak extra host memory
    is one leaf instead of the whole model."""
    main = is_main()

    def g(leaf):
        arr = gather_host_values(leaf)
        return arr if main else None

    return jax.tree.map(g, tree)


def gather_host_values(tree):
    """Fully-replicated host copy of a (possibly cross-host sharded) pytree;
    every process must call this (it is a collective)."""
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
    from jax.experimental import multihost_utils

    return jax.tree.map(
        lambda x: np.asarray(multihost_utils.process_allgather(x, tiled=True)),
        tree,
    )
