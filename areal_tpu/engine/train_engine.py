"""GSPMD train engine: one sharded model + optimizer on a device mesh.

This single engine replaces the reference's FSDP engine
(areal/engine/fsdp_engine.py:64) AND Megatron engine
(areal/engine/megatron_engine.py:67): instead of two torch backends with
hand-built process groups, parameters live as jax arrays annotated with
``NamedSharding`` over one mesh and XLA emits every collective (data-parallel
grad reduction, ZeRO-style param gathers, TP all-reduces).

Semantics kept from the reference (fsdp_engine.py:499-606,
base_hf_engine.py:257-376):

- ``train_batch`` FFD-splits a padded batch into token-budgeted microbatches,
  packs each to a 1D stream, accumulates grads across microbatches, and
  normalizes by the GLOBAL sum of ``loss_weight_fn`` over the whole batch —
  so microbatching never changes the math.
- grad-norm clipping + skip-the-step-on-nonfinite-grads.
- ``forward`` runs per-microbatch with an on-device ``post_hook`` and
  reassembles results into the original padded [B, S] layout.
- version counter for staleness bookkeeping.

TPU-native specifics: microbatches are padded to a bucket multiple so XLA
recompiles only per bucket; the packed token dim is sharded over the
(dp, cp) mesh axes so data parallelism IS sharding (no per-rank loop).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import OptimizerConfig, TrainEngineConfig
from areal_tpu.api.engine_api import TrainEngine
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, WeightUpdateMeta
from areal_tpu.models import hf_io
from areal_tpu.models.config import TransformerConfig, from_hf_config
from areal_tpu.models.lm import forward_fused_logp, forward_packed, init_params
from areal_tpu.parallel import distributed
from areal_tpu.parallel.mesh import make_mesh, single_device_mesh
from areal_tpu.parallel.pipeline import (
    check_pp_compatible,
    forward_packed_pipelined,
    pp_size,
)
from areal_tpu.parallel.sharding import FSDP_AXES, param_shardings
from areal_tpu.utils import logging, stats_tracker
from areal_tpu.utils.jax_cache import DEFAULT_DETECTOR as _retrace
from areal_tpu.utils.data import (
    TensorDict,
    pack_tensor_dict,
    pad_packed_to_multiple,
    positions_from_cu_seqlens,
    segment_ids_from_cu_seqlens,
    split_padded_tensor_dict_into_mb_list,
    unpack_sequence,
)

logger = logging.getLogger("TPUTrainEngine")


def _flat_pixels(mb):
    """Flatten the per-row image tensors into the stream-order table the
    vision encoder consumes (rows are packed in order, so images line up
    with their placeholders):
    - mini ViT:  [rows, N_img, S, S, 3] -> [rows*N_img, S, S, 3]
    - qwen2_vl:  [rows, P, pd] patch streams -> [rows*P, pd]"""
    pv = mb.get("pixel_values")
    if pv is None:
        return None
    if pv.ndim == 3:  # qwen2_vl HF-processor patch stream
        return pv.reshape((-1, pv.shape[-1]))
    return pv.reshape((-1,) + tuple(pv.shape[-3:]))

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}

# per-sequence microbatch keys whose ZERO row is a semantic no-op, so
# stacked-pp dispatch may zero-pad their row counts to a common max
# (pair_mask: a zero row is a masked pair — engine/rw/rw_engine.py)
_ZERO_ROW_IS_NOOP_KEYS = frozenset({"pair_mask"})


def _pad_rows(arrs, rmax: int | None = None):
    """Zero-pad each array's axis 0 to ``rmax`` (default: the max)."""
    if rmax is None:
        rmax = max(a.shape[0] for a in arrs)
    return [
        np.concatenate(
            [a, np.zeros((rmax - a.shape[0],) + a.shape[1:], a.dtype)]
        ) if a.shape[0] < rmax else a
        for a in arrs
    ]

# the batch keys engine.forward consumes; algorithm wrappers (PPO actor /
# critic) filter to these so per-host-different extras (rewards, behavior
# logprobs, ...) never hit the replicated device_put branch under multi-host
FORWARD_INPUT_KEYS = (
    "input_ids", "attention_mask", "pixel_values", "image_grid_thw",
)


@dataclasses.dataclass(frozen=True)
class TokenLossFn:
    """A loss that needs logits only through next-token (logp, entropy).

    When ``backend.loss_chunk_size > 0``, train/eval_batch compute these
    via the chunked fused LM head (models/lm.forward_fused_logp) instead of
    materializing [T, V] logits for the companion logits-space ``loss_fn``.
    ``fn(logp [T], entropy [T], mb) -> scalar`` must be SUM-reduced exactly
    like its logits-space twin. Frozen/hashable => stable jit-cache key.
    """

    fn: Callable
    temperature: float = 1.0
    needs_entropy: bool = False
    # critic twin: ``fn(values [T], zeros [T], mb) -> scalar`` over the
    # value head instead of (logp, entropy) over the LM head. Consumed by
    # the 1F1B pipeline schedule (the chunked-LM-head fusion itself never
    # applies to critics — values are [T, 1], nothing to chunk)
    is_value: bool = False


def make_lr_schedule(cfg: OptimizerConfig, total_steps: int):
    """constant | linear | cosine with linear warmup (reference
    base_hf_engine.py optimizer setup)."""
    sched = cfg.lr_scheduler
    warmup = max(int(sched.warmup_steps_proportion * total_steps), 0)
    decay_steps = max(total_steps - warmup, 1)
    min_lr = cfg.lr * sched.min_lr_ratio
    if sched.type == "constant":
        after = optax.constant_schedule(cfg.lr)
    elif sched.type == "linear":
        after = optax.linear_schedule(cfg.lr, min_lr, decay_steps)
    elif sched.type == "cosine":
        after = optax.cosine_decay_schedule(
            cfg.lr, decay_steps, alpha=sched.min_lr_ratio
        )
    else:
        raise ValueError(f"unknown lr_scheduler type {sched.type}")
    if warmup == 0:
        return after
    return optax.join_schedules(
        [optax.linear_schedule(0.0, cfg.lr, warmup), after], [warmup]
    )


def _scale_by_adam(b1: float, b2: float, eps: float, moment_dtype) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moments stored in ``moment_dtype`` (optax's
    only exposes mu_dtype; nu silently inherits the param dtype). Moment
    math runs in fp32; storage is cast."""

    def init(params):
        z = lambda p: jnp.zeros(p.shape, moment_dtype)  # noqa: E731
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(z, params),
            nu=jax.tree.map(z, params),
        )

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        mu = jax.tree.map(
            lambda m, g: b1 * m.astype(jnp.float32)
            + (1 - b1) * g.astype(jnp.float32),
            state.mu,
            updates,
        )
        nu = jax.tree.map(
            lambda v, g: b2 * v.astype(jnp.float32)
            + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            state.nu,
            updates,
        )
        bc1 = 1.0 - jnp.asarray(b1, jnp.float32) ** count
        bc2 = 1.0 - jnp.asarray(b2, jnp.float32) ** count
        out = jax.tree.map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu
        )
        cast = lambda t: jax.tree.map(  # noqa: E731
            lambda x: x.astype(moment_dtype), t
        )
        return out, optax.ScaleByAdamState(
            count=count, mu=cast(mu), nu=cast(nu)
        )

    return optax.GradientTransformation(init, update)


def make_optimizer(
    cfg: OptimizerConfig, total_steps: int, moment_dtype: str = "float32"
) -> optax.GradientTransformation:
    assert cfg.type in ("adamw", "sgd", "adafactor"), cfg.type
    schedule = make_lr_schedule(cfg, total_steps)

    def decay_mask(params):
        # no weight decay on 1-D leaves (norms, biases) — standard practice,
        # matches torch AdamW param-group conventions in the reference
        return jax.tree.map(lambda p: p.ndim > 1, params)

    if cfg.type == "sgd":
        return optax.chain(
            optax.clip_by_global_norm(cfg.gradient_clipping),
            optax.sgd(schedule),
        )
    if cfg.type == "adafactor":
        # factored second moments: O(rows+cols) optimizer state instead of
        # O(params) — the memory-lean choice for big models on small chips.
        # No weight decay here: optax.adafactor applies weight_decay_rate
        # AFTER lr scaling (a per-step shrink factor, not adamw-style
        # lr-scaled decoupled decay), so cfg.weight_decay would be orders of
        # magnitude too strong.
        if cfg.weight_decay:
            logger.warning(
                "adafactor ignores weight_decay=%s (unsupported semantics)",
                cfg.weight_decay,
            )
        return optax.chain(
            optax.clip_by_global_norm(cfg.gradient_clipping),
            optax.adafactor(
                learning_rate=schedule,
                multiply_by_parameter_scale=False,
                clipping_threshold=None,
                weight_decay_rate=None,
            ),
        )
    return optax.chain(
        optax.clip_by_global_norm(cfg.gradient_clipping),
        _scale_by_adam(
            b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
            moment_dtype=_DTYPES[moment_dtype],
        ),
        optax.add_decayed_weights(cfg.weight_decay, mask=decay_mask),
        optax.scale_by_learning_rate(schedule),
    )


class TPUTrainEngine(TrainEngine):
    """A sharded trainable decoder + optax optimizer on one jax Mesh."""

    def __init__(self, config: TrainEngineConfig):
        self.config = config
        self.mesh: Mesh | None = None
        self.parallel: ParallelStrategy | None = None
        self.model_config: TransformerConfig | None = None
        self.params = None
        self.opt_state = None
        self._tx: optax.GradientTransformation | None = None
        self._version = 0
        self._train_mode = True
        self._lr_schedule = None
        self._opt_steps = 0
        self._jit_cache: dict[Any, Callable] = {}
        # qwen2_vl training: the static image grid signature of the current
        # batch (one image per row, uniform grid — TPU static shapes);
        # captured by _prepare_mbs, part of every forward jit-cache key
        self._vlm_grids: tuple | None = None
        self.lora_params = None
        self._merged_cache = None
        self.attn_spec = None
        self._rollout_engine = None
        self._weight_update_meta: WeightUpdateMeta | None = None
        # delta-aware weight sync (WeightUpdateMeta.delta_only): per-leaf
        # content digests from the last SUCCESSFUL push, and the server
        # address set it reached — a changed set (new server joined the
        # rotation) forces a full re-ship, since a fresh server holds none
        # of the previously-shipped leaves
        self._wire_fingerprints: dict[str, bytes] = {}
        self._wire_fp_addrs: tuple | None = None
        # multi-host delta bookkeeping: spectators stash their fingerprint
        # updates here (only the HEAD observes whether the push actually
        # completed) and the next plan's outcome broadcast applies or
        # discards the stash; the head records the outcome it saw
        self._pending_wire_fp: dict[str, bytes] | None = None
        self._last_delta_push_ok = False
        # last _perf_stats dict, mirrored into the metrics registry by a
        # scrape-time collector (PR 8 idiom: zero steady-state cost, and
        # /metrics agrees with the stats row by construction). MFU is in
        # the dict only when the chip peak is known, so CPU rehearsal
        # exports it as ABSENT, never zero.
        self._last_perf_stats: dict[str, float] = {}
        self._metrics_collector = None
        self.initialized = False

    # ---------------------------------------------------------------- setup

    def create_process_group(self, parallel_strategy: ParallelStrategy | None = None):
        """Build the device mesh (reference: fsdp_engine.py:112-141 builds the
        dp×sp×tp DeviceMesh; here one jax Mesh with axes (pp,dp,cp,tp))."""
        self.parallel = parallel_strategy
        if parallel_strategy is None or parallel_strategy.world_size == 1:
            self.mesh = single_device_mesh()
        else:
            self.mesh = make_mesh(parallel_strategy)
        return self.mesh

    @property
    def data_parallel_size(self) -> int:
        if self.mesh is None:
            return 1
        return self.mesh.shape["dp"] * self.mesh.shape["cp"]

    def initialize(
        self,
        addr: str | None = None,
        ft_spec: FinetuneSpec | None = None,
        mesh: Mesh | None = None,
        model_config: TransformerConfig | None = None,
        seed: int = 0,
    ):
        """Load/init params, shard them, build the optimizer.

        ``model_config`` overrides HF-path config resolution (used by tests
        with tiny configs, mirroring the reference's small-model testing
        pattern at realhf/base/testing.py:37-43)."""
        if mesh is not None:
            self.mesh = mesh
        if self.mesh is None:
            self.create_process_group(None)
        cfg = self.config
        if cfg.jax_compilation_cache_dir:
            # before any jit: a relaunch after preemption (PR 4) reloads
            # compiled train-step executables from the persistent cache
            # instead of paying full recompile
            from areal_tpu.utils.jax_cache import configure_compilation_cache

            configure_compilation_cache(cfg.jax_compilation_cache_dir)
        if model_config is not None:
            self.model_config = model_config
        else:
            self.model_config = from_hf_config(cfg.path)
        check_pp_compatible(
            self.model_config, self.mesh, vpp=cfg.backend.vpp
        )
        self._pp_replicated_data = False
        if pp_size(self.mesh) > 1 and distributed.process_count() > 1:
            # Two supported multi-host pp data placements, decided by the
            # mesh's device->process layout (parallel/mesh.py):
            # (a) pp spans hosts, each host's devices cover EVERY (dp,cp)
            #     shard -> synchronized-batch mode: all hosts feed the
            #     IDENTICAL batch (verified by checksum each step) and the
            #     loss normalizer must NOT be summed across processes.
            # (b) dp-outer layout: each host's devices cover a distinct
            #     (dp,cp) slice across all stages -> every host feeds its
            #     OWN data shard (the reference's Megatron dp x pp layout);
            #     the normal multi-host sync path applies.
            devs = self.mesh.devices  # [pp, dp, cp, tp]
            me = jax.process_index()
            n_dp, n_cp = devs.shape[1], devs.shape[2]
            local = {
                (i, j)
                for i in range(n_dp)
                for j in range(n_cp)
                if any(d.process_index == me for d in devs[:, i, j, :].flat)
            }
            if len(local) == n_dp * n_cp:
                self._pp_replicated_data = True
            else:
                owners = []
                for i in range(n_dp):
                    for j in range(n_cp):
                        procs = {
                            d.process_index for d in devs[:, i, j, :].flat
                        }
                        if len(procs) != 1:
                            raise NotImplementedError(
                                "pp>1 multi-host needs each (dp,cp) data "
                                "shard either fully local to one process "
                                "(dp-outer layout) or covered by every "
                                f"process (sync-batch); shard ({i},{j}) "
                                f"spans processes {sorted(procs)}"
                            )
                        owners.append(procs.pop())
                if owners != sorted(owners):
                    # host token streams concatenate in process order; a
                    # permuted shard->process map would interleave them
                    raise NotImplementedError(
                        "pp>1 multi-host dp shards must follow process "
                        f"order along (dp, cp); got owners {owners}"
                    )
        self.attn_spec = self._build_attn_spec()

        if cfg.optimizer is not None and cfg.optimizer.offload_optimizer_state:
            # refuse rather than silently ignore: adam state stays on
            # device until host-offload lands
            raise NotImplementedError(
                "optimizer.offload_optimizer_state is not implemented by "
                "the JAX backend; set it to False"
            )
        param_dtype = _DTYPES[cfg.backend.param_dtype]
        shardings = self.param_shardings()
        if cfg.init_from_scratch or not cfg.path:
            key = jax.random.PRNGKey(seed)
            init = jax.jit(
                lambda k: init_params(self.model_config, k, dtype=param_dtype),
                out_shardings=shardings,
            )
            self.params = init(key)
        else:
            _, self.params = hf_io.load_hf_params(
                cfg.path,
                self.model_config,
                dtype=cfg.backend.param_dtype,
                to_device=self._sharded_putter(shardings),
            )

        if cfg.lora is not None:
            # adapters are the ONLY trainable tree; the base stays frozen and
            # the effective weights are merged on the fly (models/lora.py)
            from areal_tpu.models.lora import init_lora_params

            rep = NamedSharding(self.mesh, P())
            self.lora_params = jax.device_put(
                init_lora_params(
                    self.model_config, cfg.lora, jax.random.PRNGKey(seed + 1)
                ),
                rep,
            )
        else:
            self.lora_params = None

        if cfg.optimizer is not None:
            total = ft_spec.total_train_steps if ft_spec is not None else 1 << 20
            self._tx = make_optimizer(
                cfg.optimizer, total, moment_dtype=cfg.backend.optimizer_dtype
            )
            self._lr_schedule = make_lr_schedule(cfg.optimizer, total)
            init_opt = jax.jit(self._tx.init)
            self.opt_state = init_opt(self._trainable())
        self._register_perf_collector()
        self.initialized = True
        return self

    def _register_perf_collector(self):
        """Expose the analytic throughput/MFU of the last train_batch on
        the unified metrics registry (satellite of the goodput
        observatory): a collector copies ``self._last_perf_stats`` into
        device-kind-labelled gauges AT SCRAPE TIME only — the train step
        itself pays nothing beyond storing the dict it already builds.
        These are the COMPUTE-window numbers (train_batch wall); the
        StepTimeline exports the whole-step goodput twins."""
        from areal_tpu.utils import metrics as _metrics
        from areal_tpu.utils import perf as _perf

        reg = _metrics.DEFAULT_REGISTRY
        gauges = {
            "tokens_per_sec": reg.gauge(
                "areal_train_compute_tokens_per_sec",
                "trained tokens/s over the last train_batch wall",
                labels=("device_kind",),
            ),
            "tflops_per_chip": reg.gauge(
                "areal_train_compute_tflops_per_chip",
                "analytic TFLOP/s per chip over the last train_batch",
                labels=("device_kind",),
            ),
            "mfu": reg.gauge(
                "areal_train_compute_mfu",
                "model FLOPs utilization of the last train_batch "
                "(absent when the chip peak is unknown — CPU rehearsal)",
                labels=("device_kind",),
            ),
        }
        kind = _perf.device_kind()

        def _collect(_reg, _self=self, _gauges=gauges, _kind=kind):
            stats = _self._last_perf_stats
            for key, gauge in _gauges.items():
                v = stats.get(key)
                if v is not None:
                    gauge.labels(device_kind=_kind).set(v)

        self._metrics_collector = reg.register_collector(_collect)

    def _trainable(self):
        """The pytree the optimizer updates: LoRA adapters when configured,
        else the full params."""
        return self.lora_params if self.config.lora is not None else self.params

    def _set_trainable(self, tree):
        if self.config.lora is not None:
            self.lora_params = tree
            self._merged_cache = None  # effective weights changed
        else:
            self.params = tree

    def effective_params(self):
        """Merged (base + adapter) weights for scoring / export / serving;
        identity without LoRA. Cached until the next optimizer step."""
        if self.config.lora is None:
            return self.params
        if self._merged_cache is None:
            from areal_tpu.models.lora import merge_lora

            key = "lora_merge"
            if key not in self._jit_cache:
                cfg = self.config.lora
                self._jit_cache[key] = jax.jit(
                    lambda b, lo: merge_lora(b, lo, cfg)
                )
            self._merged_cache = self._jit_cache[key](
                self.params, self.lora_params
            )
        return self._merged_cache

    def _build_attn_spec(self):
        """Per-engine attention dispatch (no process-global state): tokens
        ring over (dp, cp) when sharded — exactly equal to global packed
        attention, O((T/n)^2) memory — and heads shard over tp when the
        head counts divide, keeping the Pallas flash kernel live under TP
        instead of falling back to O(T^2) einsum attention."""
        from areal_tpu.ops.attention import AttnSpec

        return AttnSpec.for_mesh(
            self.mesh, self.model_config, impl=self.config.attn_impl
        )

    def destroy(self):
        self.attn_spec = None  # drop the mesh reference
        self.params = None
        self.opt_state = None
        self._jit_cache.clear()
        if self._metrics_collector is not None:
            from areal_tpu.utils import metrics as _metrics

            _metrics.DEFAULT_REGISTRY.unregister_collector(
                self._metrics_collector
            )
            self._metrics_collector = None
        self.initialized = False

    # ------------------------------------------------------------- plumbing

    def _sharded_putter(self, shardings):
        """fn(path, np_array) -> sharded jax array, for hf_io streaming load."""
        flat = dict(jax.tree_util.tree_flatten_with_path(shardings)[0])

        def to_device(path, arr):
            return jax.device_put(arr, flat[path])

        return to_device

    def param_shardings(self):
        shapes = jax.eval_shape(
            lambda: init_params(self.model_config, jax.random.PRNGKey(0))
        )
        return param_shardings(self.mesh, shapes, fsdp=self.config.backend.fsdp)

    def train(self, mode: bool = True):
        self._train_mode = mode
        return self

    def get_version(self) -> int:
        return self._version

    def set_version(self, version: int):
        self._version = version

    def step_lr_scheduler(self):
        """No-op: the optax schedule advances with the optimizer step count
        (kept for API parity with the reference's explicit scheduler)."""

    def _perf_stats(
        self, input_: TensorDict, real_tokens: int, step_time: float
    ) -> dict[str, float]:
        """Analytic throughput/MFU per step (reference:
        realhf/base/monitor.py:288-403 FLOPs counters)."""
        from areal_tpu.utils import perf

        if step_time <= 0 or real_tokens <= 0:
            return {}
        real_tokens = distributed.sync_sum(real_tokens)
        n_seqs = distributed.sync_sum(
            max(int(np.asarray(input_["attention_mask"]).shape[0]), 1)
        )
        avg_seqlen = real_tokens / n_seqs
        fpt = perf.train_flops_per_token(self.model_config, avg_seqlen)
        tps = real_tokens / step_time
        n_chips = self.mesh.size if self.mesh is not None else 1
        out = {
            "tokens_per_sec": tps,
            "tflops_per_chip": tps * fpt / n_chips / 1e12,
        }
        m = perf.mfu(tps, fpt, n_chips=n_chips)
        if m is not None:
            out["mfu"] = m
        # the registry collector reads this at scrape time (no push here)
        self._last_perf_stats = out
        return out

    def current_lr(self) -> float:
        if self._lr_schedule is None:
            return 0.0
        return float(self._lr_schedule(self._opt_steps))

    # --------------------------------------------------------- device plumbing

    def _mb_to_device(self, packed: TensorDict) -> dict[str, jnp.ndarray]:
        """Move one packed microbatch to the mesh. Token-dim arrays shard over
        (dp, cp); everything else replicates. cu_seqlens stays host-side.

        Multi-host: this process's ``packed`` holds only its LOCAL token
        stream; the global sharded array is assembled host-locally (each
        host feeds its own device shards — no cross-host data movement,
        the DistRolloutCoordinator redistribution made structural)."""
        n = int(packed["cu_seqlens"][-1])
        if "pixel_values" in packed and distributed.process_count() > 1:
            # assemble the GLOBAL image table in process order — the same
            # order host token streams concatenate into the global stream —
            # so splice_image_embeds' global placeholder ranks line up.
            # The table replicates to every host (each device encodes all
            # images); fine at rollout-batch scale, revisit if image counts
            # explode. Every host must carry a pixel_values key (VLM
            # datasets always do) or the collective would desync.
            flat = np.asarray(_flat_pixels(packed), np.float32)
            packed = dict(packed)
            packed["pixel_values"] = distributed.allgather_rows(flat)
        rep = NamedSharding(self.mesh, P())
        out = {}
        for k, v in packed.items():
            if k in ("cu_seqlens", "max_seqlen", "image_grid_thw"):
                continue
            arr = np.asarray(v)
            if k == "pixel_values":
                # the (possibly allgathered) image table is ALWAYS
                # replicated — never token-sharded, even if its row count
                # coincides with this host's token count n
                out[k] = jax.device_put(arr.astype(np.float32), rep)
            elif arr.ndim >= 1 and arr.shape[0] == n:
                if arr.dtype == np.float64:
                    arr = arr.astype(np.float32)
                if arr.dtype == np.int64:
                    arr = arr.astype(np.int32)
                spec = P(*([FSDP_AXES] + [None] * (arr.ndim - 1)))
                out[k] = distributed.host_local_to_global(self.mesh, spec, arr)
            else:
                # non-token arrays replicate; in multi-host mode every
                # process must pass identical values here
                out[k] = jax.device_put(
                    arr.astype(np.float32) if arr.dtype == np.float64 else arr, rep
                )
        return out

    def _stacked_to_device(self, packed_mbs: list[TensorDict]) -> dict:
        """Stack equal-bucket packed microbatches into one [M, T, ...] batch
        on the mesh (the pipelined grad step consumes all mbs in one call).
        Token dims shard over (dp, cp); the leading M dim stays unsharded —
        it is the pipeline's time axis, not a data axis."""
        assert packed_mbs, "no microbatches"
        n = int(packed_mbs[0]["cu_seqlens"][-1])
        if any(int(p["cu_seqlens"][-1]) != n for p in packed_mbs):
            raise ValueError("stacked microbatches must share one bucket")
        rep = NamedSharding(self.mesh, P())
        out = {}
        if any("pixel_values" in p for p in packed_mbs):
            if not all("pixel_values" in p for p in packed_mbs):
                raise NotImplementedError(
                    "pp>1 VLM needs every microbatch to carry pixel_values "
                    "(mixed text/image microbatch splits are unsupported)"
                )
            # pad per-mb image tables with ghost rows to a common Pmax and
            # stack [M, Pmax, ...]; ghost rows encode garbage the
            # placeholder-rank splice never reads (lm.embed_with_images)
            tables = [
                np.asarray(_flat_pixels(p), np.float32) for p in packed_mbs
            ]
            pmax = max(t.shape[0] for t in tables)
            if self.model_config.is_qwen_vl and self._vlm_grids:
                # ghost rows must form WHOLE ghost images: the qwen2_vl
                # image count derives as P // prod(grid) inside the trace
                gt, gh, gw = self._vlm_grids
                ppi = gt * gh * gw
                pmax = -(-pmax // ppi) * ppi
            out["pixel_values"] = jax.device_put(
                np.stack(_pad_rows(tables, pmax)), rep
            )
        for k in packed_mbs[0]:
            if k in ("cu_seqlens", "max_seqlen", "image_grid_thw",
                     "pixel_values"):
                continue
            arrs = [np.asarray(p[k]) for p in packed_mbs]
            if any(a.shape != arrs[0].shape for a in arrs[1:]):
                shapes = [a.shape for a in arrs]
                if k in _ZERO_ROW_IS_NOOP_KEYS and all(
                    a.shape[1:] == arrs[0].shape[1:] for a in arrs
                ):
                    # per-SEQUENCE keys whose zero row is verified a no-op
                    # (pair_mask: a zero row is a masked pair) zero-pad to
                    # the max row count; anything else stays fail-loud
                    arrs = _pad_rows(arrs)
                else:
                    raise NotImplementedError(
                        f"pp>1 cannot stack microbatch key {k!r}: per-mb "
                        f"shapes {shapes} differ (only keys in "
                        f"{sorted(_ZERO_ROW_IS_NOOP_KEYS)} may row-pad)"
                    )
            arr = np.stack(arrs)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            if arr.dtype == np.int64:
                arr = arr.astype(np.int32)
            if arr.ndim >= 2 and arr.shape[1] == n:
                spec = P(*([None, FSDP_AXES] + [None] * (arr.ndim - 2)))
                out[k] = distributed.host_local_to_global(
                    self.mesh, spec, arr
                )
            else:
                out[k] = jax.device_put(arr, rep)
        return out

    @staticmethod
    def _repad_packed(packed: TensorDict, target: int) -> TensorDict:
        """Re-pad one packed microbatch to exactly ``target`` tokens and
        rebuild positions/segment_ids (the pad tokens form an isolated
        zero-loss segment)."""
        if int(packed["cu_seqlens"][-1]) >= target:
            return packed
        packed = dict(packed)
        for k in ("positions", "segment_ids"):
            packed.pop(k, None)
        packed, _ = pad_packed_to_multiple(packed, target)
        cu = packed["cu_seqlens"]
        total = int(cu[-1])
        packed["positions"] = positions_from_cu_seqlens(cu, total)
        packed["segment_ids"] = segment_ids_from_cu_seqlens(cu, total)
        return packed

    def _prepare_mbs(
        self, input_: TensorDict, group_size: int = 1
    ) -> tuple[Any, list[TensorDict], list[int]]:
        """Padded batch -> packed, bucketed microbatches (host side).

        Reference: base_hf_engine.prepare_mb_list (base_hf_engine.py:257-376).
        Returns (MicroBatchList, packed mbs with positions/segment_ids, real
        token counts). ``group_size`` keeps row groups (e.g. RM pairs) in one
        microbatch."""
        if self.model_config.is_qwen_vl:
            if "image_grid_thw" in input_:
                # batch-wide static grid signature, captured BEFORE the mb
                # split: all microbatches share one jitted forward, so one
                # grid must cover them all
                self._capture_vlm_grids(input_)
            else:
                # text-only batch: a stale grid would needlessly key (and
                # recompile) the text-only jit functions
                self._vlm_grids = None
        mb_list = split_padded_tensor_dict_into_mb_list(
            input_,
            max_tokens_per_mb=self.config.mb_spec.max_tokens_per_mb,
            min_n_mbs=self.config.mb_spec.n_mbs,
            # config-declared adjacency (mb_spec.granularity, e.g. GRPO
            # groups) composes with the caller's structural grouping
            group_size=max(
                group_size, int(self.config.mb_spec.granularity or 1)
            ),
        )
        multiple = self.config.backend.pad_mb_to_multiple
        packed_mbs, real_ns = [], []
        for mb in mb_list.mbs:
            packed = pack_tensor_dict(mb)
            packed, real_n = pad_packed_to_multiple(packed, multiple)
            cu = packed["cu_seqlens"]
            total = int(cu[-1])
            mc = self.model_config
            if (
                mc.pos_embed_type == "learned"
                or mc.rope_scaling_type == "dynamic"
            ):
                longest = int(np.diff(np.asarray(cu)).max())
                if longest > mc.max_position_embeddings:
                    # learned: the wpe gather clamps out-of-range rows
                    # silently; dynamic NTK: beyond the window HF
                    # re-stretches the base per seq_len, which the static
                    # compiled schedule cannot — logprobs would silently
                    # diverge from HF/inference
                    raise ValueError(
                        f"sequence of {longest} tokens exceeds "
                        f"max_position_embeddings "
                        f"({mc.max_position_embeddings}) for "
                        f"{'learned positions' if mc.pos_embed_type == 'learned' else 'dynamic-NTK rope'}"
                    )
            packed["positions"] = positions_from_cu_seqlens(cu, total)
            seg = segment_ids_from_cu_seqlens(cu, total)
            # tokens beyond real_n belong to the alignment-pad sequence; give
            # them a real segment id (isolated) but they carry zero loss_mask
            packed["segment_ids"] = seg
            if (
                self.model_config.is_qwen_vl
                and "pixel_values" in packed
            ):
                packed["positions"] = self._mrope_positions_packed(packed)
            packed_mbs.append(packed)
            real_ns.append(real_n)
        if pp_size(self.mesh) > 1:
            # the pipelined grad step stacks microbatches into one [M, T]
            # batch, so every mb must share ONE token bucket
            t = max(int(p["cu_seqlens"][-1]) for p in packed_mbs)
            if distributed.process_count() > 1:
                t = int(distributed.sync_max(t))
            old_mbs = packed_mbs
            packed_mbs = [self._repad_packed(p, t) for p in packed_mbs]
            if self.model_config.is_qwen_vl:
                # _repad_packed rebuilt PLAIN positions; qwen2_vl mbs need
                # their [3, T] M-RoPE streams recomputed over the new
                # bucket (only where repadding actually happened —
                # _repad_packed returns the SAME object when the mb was
                # already at the target bucket)
                for old, p in zip(old_mbs, packed_mbs):
                    if p is not old and "pixel_values" in p:
                        p["positions"] = self._mrope_positions_packed(p)
        if self._pp_replicated_data:
            # synchronized-batch multi-host pp: every host MUST be feeding
            # the identical batch — a silent divergence would build
            # inconsistent pp-replicated global arrays. One vectorized
            # collective checks (count, tokens, input_ids checksum).
            # ORDER-SENSITIVE signature (a permutation of the same
            # microbatches must fail too): position- and token-weighted
            # rolling hashes of ids + loss_mask, kept exactly float64-
            # representable via mod 2^40
            mod = np.int64(1) << 40

            def h(arr_key):
                acc = np.int64(0)
                for i, p in enumerate(packed_mbs):
                    a = np.asarray(p[arr_key], np.int64).ravel()
                    w = np.arange(1, a.size + 1, dtype=np.int64) % mod
                    acc = (acc + np.int64(i + 1) * np.sum(a * w % mod)) % mod
                return float(acc)

            sig = np.asarray(
                [
                    len(packed_mbs),
                    h("input_ids"),
                    h("loss_mask"),
                    sum(int(p["cu_seqlens"][-1]) for p in packed_mbs),
                ],
                np.float64,
            )
            mx = distributed.sync_max_vector(sig, 4)
            mn = -distributed.sync_max_vector(-sig, 4)
            if not np.array_equal(mx, mn):
                raise ValueError(
                    "multi-host pp requires every host to feed the IDENTICAL "
                    f"batch (synchronized-batch mode); local signature {sig} "
                    f"vs fleet max {mx} / min {mn}"
                )
        elif distributed.process_count() > 1:
            packed_mbs, real_ns = self._sync_mbs_across_hosts(packed_mbs, real_ns)
        return mb_list, packed_mbs, real_ns

    def _capture_vlm_grids(self, packed: TensorDict) -> None:
        """Static grid signature for the qwen2_vl forward jit (one image per
        row, uniform grid across the microbatch — the TPU static-shape
        contract, like the mini ViT's fixed vision_patches)."""
        if distributed.process_count() > 1:
            raise NotImplementedError(
                "qwen2_vl training under multi-host jax.distributed is not "
                "supported yet (per-host grid/image-table alignment)"
            )
        grids = {
            tuple(int(v) for v in row)
            for row in np.asarray(packed["image_grid_thw"]).reshape(-1, 3)
        }
        if len(grids) != 1:
            raise NotImplementedError(
                f"qwen2_vl training needs one uniform image grid per batch "
                f"(static shapes); got {sorted(grids)}"
            )
        # a single (t, h, w) — per-microbatch image COUNTS derive from the
        # pixel-array shape inside the trace, so one jit covers every mb
        self._vlm_grids = grids.pop()

    def _mrope_positions_packed(self, packed: TensorDict) -> np.ndarray:
        """[3, T] M-RoPE positions for a packed qwen2_vl stream: per-sequence
        vlm_qwen2.mrope_positions (offset-free per segment), pad sequences
        get plain arange (isolated zero-loss segments)."""
        from areal_tpu.models.vlm_qwen2 import mrope_positions

        cu = np.asarray(packed["cu_seqlens"])
        ids = np.asarray(packed["input_ids"])
        tok = self.model_config.image_token_id
        parts = []
        for i in range(len(cu) - 1):
            row = ids[cu[i]: cu[i + 1]]
            is_ph = row == tok
            # one grid per placeholder RUN (multi-image rows supported as
            # long as every image shares the batch grid)
            n_runs = int(
                np.count_nonzero(
                    is_ph & np.concatenate([[True], ~is_ph[:-1]])
                )
            )
            if n_runs:
                parts.append(
                    mrope_positions(
                        self.model_config, row, [self._vlm_grids] * n_runs
                    )
                )
            else:  # text-only or alignment-pad sequence
                parts.append(
                    np.broadcast_to(
                        np.arange(len(row), dtype=np.int64), (3, len(row))
                    )
                )
        return np.concatenate(parts, axis=1).astype(np.int32)

    def _sync_mbs_across_hosts(
        self, packed_mbs: list[TensorDict], real_ns: list[int]
    ):
        """Multi-host agreement on microbatch count and bucket lengths.

        Each host packed only its LOCAL sequences; jit shapes must line up
        globally (the reference's allocate_balanced_mbs_synced role,
        areal/utils/data.py:249). Hosts that run short fabricate a zero-loss
        clone of their last microbatch (real_n = 0). Two collectives total:
        one for the count, one vectorized over all bucket lengths."""
        n_mbs = int(distributed.sync_max(len(packed_mbs)))
        real_ns = list(real_ns)
        while len(packed_mbs) < n_mbs:
            dummy = dict(packed_mbs[-1])
            dummy["loss_mask"] = np.zeros_like(np.asarray(dummy["loss_mask"]))
            packed_mbs.append(dummy)
            real_ns.append(0)
        local_ts = [int(np.asarray(p["cu_seqlens"])[-1]) for p in packed_mbs]
        targets = distributed.sync_max_vector(local_ts, n_mbs)
        out = []
        for packed, local_t, target in zip(packed_mbs, local_ts, targets):
            packed = self._repad_packed(packed, int(target))
            # per-host segment-id namespace: host-local ids all start at 0,
            # and the global packed stream concatenates hosts — without an
            # offset, host B's sequence 0 would attend into host A's
            # sequence 0 (they'd share a segment id)
            seg = np.asarray(packed["segment_ids"])
            offset = distributed.process_index() << 20
            packed = dict(packed)
            packed["segment_ids"] = np.where(seg >= 0, seg + offset, seg).astype(
                np.int32
            )
            out.append(packed)
        return out, real_ns

    # ------------------------------------------------------------ train step

    def _grad_fn_pp(
        self, loss_fn: Callable, token_loss_fn: "TokenLossFn | None" = None
    ) -> Callable:
        """Pipelined grad step: ALL microbatches ride one jit call as a
        stacked [M, T] batch; the GPipe schedule inside
        forward_packed_pipelined overlaps their stage compute, and grad
        accumulation over M falls out of summing the vmapped per-mb losses
        (no explicit accumulator buffer)."""
        key = ("grad_pp", loss_fn, token_loss_fn, self._vlm_grids)
        if key not in self._jit_cache:
            cfg, backend = self.model_config, self.config.backend
            mesh, attn_spec = self.mesh, self.attn_spec
            acc_dtype = _DTYPES[backend.grad_acc_dtype]
            lora_cfg = self.config.lora

            if backend.pp_schedule == "1f1b" and cfg.is_vlm:
                logger.warning(
                    "pp_schedule=1f1b does not support vision towers (the "
                    "tower runs outside the gpipe conveyor); falling back "
                    "to gpipe"
                )
            elif (
                backend.pp_schedule == "1f1b"
                and token_loss_fn is not None
                and (not cfg.is_critic or token_loss_fn.is_value)
            ):
                from areal_tpu.parallel.pipeline import (
                    pipeline_train_step_1f1b,
                )

                def run_1f1b(params, mbs):
                    return pipeline_train_step_1f1b(
                        params, cfg, mbs, mesh, token_loss_fn,
                        attn_spec=attn_spec,
                        remat=backend.remat,
                        remat_policy=backend.remat_policy,
                        acc_dtype=acc_dtype,
                        vpp=backend.vpp,
                    )

                if lora_cfg is None:
                    self._jit_cache[key] = jax.jit(
                        _retrace.wrap("train_engine.grad_step_1f1b", run_1f1b)
                    )
                else:
                    from areal_tpu.models.lora import merge_lora

                    def step_lora(lora, base, mbs):
                        # the merge is LINEAR in the adapters, so pulling
                        # the hand-rolled schedule's dL/dW_merged back
                        # through one vjp of the merge gives exact
                        # dL/dlora — LoRA rides 1F1B without the schedule
                        # knowing adapters exist
                        merged, pull = jax.vjp(
                            lambda lo: merge_lora(base, lo, lora_cfg), lora
                        )
                        losses, g_merged = run_1f1b(merged, mbs)
                        (g_lora,) = pull(jax.tree.map(
                            lambda g, w: g.astype(w.dtype), g_merged, merged
                        ))
                        return losses, jax.tree.map(
                            lambda g: g.astype(acc_dtype), g_lora
                        )

                    jitted = jax.jit(step_lora)
                    self._jit_cache[key] = (
                        lambda tr, mbs: jitted(tr, self.params, mbs)
                    )
                return self._jit_cache[key]
            if backend.pp_schedule == "1f1b" and not cfg.is_vlm:
                logger.warning(
                    "pp_schedule=1f1b needs the fused-loss contract "
                    "(TokenLossFn; is_value=True for critics); falling "
                    "back to gpipe"
                )
            elif backend.pp_schedule not in ("gpipe", "1f1b"):
                raise ValueError(
                    f"unknown pp_schedule {backend.pp_schedule!r}; "
                    "use gpipe | 1f1b"
                )

            vlm_grids = self._vlm_grids

            def compute(params, mbs):
                params = self._cast_for_compute(params)
                logits = forward_packed_pipelined(
                    params,
                    cfg,
                    mbs["input_ids"],
                    mbs["positions"],
                    mbs["segment_ids"],
                    mesh,
                    attn_spec=attn_spec,
                    remat=backend.remat,
                    remat_policy=backend.remat_policy,
                    vpp=backend.vpp,
                    pixel_values=mbs.get("pixel_values"),
                    image_grid_thw=vlm_grids,
                )
                losses = jax.vmap(loss_fn)(logits, mbs)  # [M]
                return jnp.sum(losses), losses

            if lora_cfg is None:

                def step(params, mbs):
                    (_, losses), grads = jax.value_and_grad(
                        compute, has_aux=True
                    )(params, mbs)
                    grads = jax.tree.map(lambda g: g.astype(acc_dtype), grads)
                    return losses, grads

                self._jit_cache[key] = jax.jit(
                    _retrace.wrap("train_engine.grad_step_pp", step)
                )
            else:
                from areal_tpu.models.lora import merge_lora

                def step(lora, base, mbs):
                    def f(lo):
                        return compute(merge_lora(base, lo, lora_cfg), mbs)

                    (_, losses), grads = jax.value_and_grad(f, has_aux=True)(
                        lora
                    )
                    grads = jax.tree.map(lambda g: g.astype(acc_dtype), grads)
                    return losses, grads

                jitted = jax.jit(step)
                self._jit_cache[key] = (
                    lambda tr, mbs: jitted(tr, self.params, mbs)
                )
        return self._jit_cache[key]

    def _grad_fn(self, loss_fn: Callable) -> Callable:
        key = ("grad", loss_fn, self._vlm_grids)
        if key not in self._jit_cache:
            cfg, backend = self.model_config, self.config.backend

            def compute(params, mb):
                params = self._cast_for_compute(params)
                logits = forward_packed(
                    params,
                    cfg,
                    mb["input_ids"],
                    mb["positions"],
                    mb["segment_ids"],
                    remat=backend.remat,
                    remat_policy=backend.remat_policy,
                    attn_spec=self.attn_spec,
                    pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                )
                return loss_fn(logits, mb)

            self._jit_cache[key] = self._build_grad_step(compute)
        return self._jit_cache[key]

    def _grad_fn_fused(self, token_loss_fn: "TokenLossFn") -> Callable:
        """Like _grad_fn but with the chunked LM-head loss
        (models/lm.forward_fused_logp): [T, V] logits never materialize."""
        key = ("grad_fused", token_loss_fn, self._vlm_grids)
        if key not in self._jit_cache:
            cfg, backend = self.model_config, self.config.backend

            def compute(params, mb):
                params = self._cast_for_compute(params)
                logp, ent = forward_fused_logp(
                    params,
                    cfg,
                    mb["input_ids"],
                    mb["positions"],
                    mb["segment_ids"],
                    labels=jnp.roll(mb["input_ids"], shift=-1),
                    temperature=token_loss_fn.temperature,
                    need_entropy=token_loss_fn.needs_entropy,
                    chunk=backend.loss_chunk_size,
                    remat=backend.remat,
                    remat_policy=backend.remat_policy,
                    attn_spec=self.attn_spec,
                    pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                )
                return token_loss_fn.fn(logp, ent, mb)

            self._jit_cache[key] = self._build_grad_step(compute)
        return self._jit_cache[key]

    def _use_fused_loss(self, token_loss_fn) -> bool:
        return (
            token_loss_fn is not None
            and self.config.backend.loss_chunk_size > 0
            and pp_size(self.mesh) == 1
            and not self.model_config.is_critic
        )

    def _cast_for_compute(self, params):
        """An explicit ``backend.compute_dtype`` != ``param_dtype`` casts
        floating params at the top of each forward; the default (unset, or
        equal dtypes) returns params untouched, so the jaxpr is unchanged."""
        backend = self.config.backend
        target = backend.compute_dtype or backend.param_dtype
        if target == backend.param_dtype:
            return params
        dt = _DTYPES[target]
        return jax.tree.map(
            lambda p: p.astype(dt)
            if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            params,
        )

    def _build_grad_step(self, compute: Callable) -> Callable:
        backend = self.config.backend
        acc_dtype = _DTYPES[backend.grad_acc_dtype]
        lora_cfg = self.config.lora

        if lora_cfg is None:

            def step(params, acc, mb):
                loss, grads = jax.value_and_grad(compute)(params, mb)
                acc = jax.tree.map(
                    lambda a, g: a + g.astype(acc_dtype), acc, grads
                )
                return loss, acc

            # _retrace.wrap: trace-count telemetry only (the wrapper body
            # runs solely when jax traces — a re-trace after the timeline's
            # warmup freeze is the silent shape-bucket-miss signal)
            return jax.jit(
                _retrace.wrap("train_engine.grad_step", step),
                donate_argnums=(1,),
            )
        from areal_tpu.models.lora import merge_lora

        def step(lora, base, acc, mb):
            def f(lo):
                return compute(merge_lora(base, lo, lora_cfg), mb)

            loss, grads = jax.value_and_grad(f)(lora)
            acc = jax.tree.map(
                lambda a, g: a + g.astype(acc_dtype), acc, grads
            )
            return loss, acc

        jitted = jax.jit(
            _retrace.wrap("train_engine.grad_step_lora", step),
            donate_argnums=(2,),
        )
        return lambda tr, acc, mb: jitted(tr, self.params, acc, mb)

    def _apply_fn(self) -> Callable:
        key = "apply"
        if key not in self._jit_cache:
            tx = self._tx

            def apply(params, opt_state, grads, denom):
                grads = jax.tree.map(lambda g: g / denom, grads)
                gnorm = optax.global_norm(grads)
                updates, new_state = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                ok = jnp.isfinite(gnorm)
                sel = lambda n, o: jnp.where(ok, n, o)
                new_params = jax.tree.map(sel, new_params, params)
                new_state = jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o)
                    if hasattr(n, "dtype")
                    else n,
                    new_state,
                    opt_state,
                )
                return new_params, new_state, gnorm, ok

            self._jit_cache[key] = jax.jit(
                _retrace.wrap("train_engine.apply", apply),
                # donate_params=False keeps the pre-step params buffer
                # alive (debug/what-if reads) at the cost of a full extra
                # params copy; grads/opt_state are always donated
                donate_argnums=(0, 1, 2)
                if self.config.backend.donate_params
                else (1, 2),
            )
        return self._jit_cache[key]

    def _finalize_fn(self) -> Callable:
        key = "finalize"
        if key not in self._jit_cache:

            def fin(gnorm, ok, losses, lr):
                return jnp.stack(
                    [
                        jnp.asarray(gnorm, jnp.float32),
                        jnp.asarray(ok, jnp.float32),
                        jnp.sum(
                            jnp.stack(
                                [jnp.asarray(l, jnp.float32) for l in losses]
                            )
                        ),
                        jnp.asarray(lr, jnp.float32),
                    ]
                )

            self._jit_cache[key] = jax.jit(fin)
        return self._jit_cache[key]

    def _zeros_like_grads(self):
        key = "zeros"
        if key not in self._jit_cache:
            acc_dtype = _DTYPES[self.config.backend.grad_acc_dtype]
            kwargs = {}
            if self.config.lora is None:
                kwargs["out_shardings"] = self.param_shardings()
            self._jit_cache[key] = jax.jit(
                lambda p: jax.tree.map(
                    lambda x: jnp.zeros(x.shape, acc_dtype), p
                ),
                **kwargs,
            )
        return self._jit_cache[key](self._trainable())

    def train_batch(
        self,
        input_: TensorDict,
        loss_fn: Callable,
        loss_weight_fn: Callable,
        group_size: int = 1,
        token_loss_fn: "TokenLossFn | None" = None,
    ) -> dict[str, float]:
        """Grad-accumulated optimizer step over one padded batch.

        The per-token loss normalizer is global: each microbatch contributes
        sum-reduced loss gradients and the total is divided by
        ``sum(loss_weight_fn(mb))`` (reference: fsdp_engine.py:536-560)."""
        assert self.initialized and self._tx is not None
        t0 = time.perf_counter()
        mb_list, packed_mbs, real_ns = self._prepare_mbs(input_, group_size=group_size)
        real_tokens = int(sum(real_ns))
        weights = [float(loss_weight_fn(mb)) for mb in packed_mbs]
        # multi-host: the normalizer is the GLOBAL loss weight (each host
        # only sees its local sequences; reference fsdp_engine.py:536-560
        # scales by dp_size for the same reason). Synchronized-batch pp is
        # the exception: hosts feed REPLICAS, so summing across processes
        # would double-count the denominator.
        if self._pp_replicated_data:
            total_weight = float(sum(weights))
        else:
            total_weight = distributed.sync_sum(sum(weights))
        assert total_weight > 0, "loss_weight_fn summed to 0 over the batch"

        # free any merged-weights copy BEFORE forward+backward: holding a
        # full effective-params clone through the grad step would forfeit
        # LoRA's memory savings
        self._merged_cache = None
        if pp_size(self.mesh) > 1:
            mbs_dev = self._stacked_to_device(packed_mbs)
            losses_vec, acc = self._grad_fn_pp(loss_fn, token_loss_fn)(
                self._trainable(), mbs_dev
            )
            losses = [jnp.sum(losses_vec)]
        else:
            if self._use_fused_loss(token_loss_fn):
                grad_step = self._grad_fn_fused(token_loss_fn)
            else:
                grad_step = self._grad_fn(loss_fn)
            acc = self._zeros_like_grads()
            losses = []
            for packed in packed_mbs:
                mb_dev = self._mb_to_device(packed)
                loss, acc = grad_step(self._trainable(), acc, mb_dev)
                losses.append(loss)

        apply = self._apply_fn()
        new_trainable, self.opt_state, gnorm, ok = apply(
            self._trainable(), self.opt_state, acc, jnp.float32(total_weight)
        )
        self._set_trainable(new_trainable)
        # All per-step scalars (grad norm, skip flag, summed loss, lr) ride
        # ONE packed vector fetched in a single device->host read: on a
        # tunneled/remote backend every scalar read is a full RTT (~50ms),
        # and four separate float()/bool() calls were costing ~20% of the
        # whole 1.5B-model step.
        lr_val = (
            self._lr_schedule(self._opt_steps)
            if self._lr_schedule is not None
            else 0.0
        )
        host = np.asarray(self._finalize_fn()(gnorm, ok, losses, lr_val))
        gnorm_f = float(host[0])
        ok_b = bool(host[1])
        loss_sum = float(host[2])
        if ok_b:
            self._opt_steps += 1
        step_time = time.perf_counter() - t0
        stats = {
            "loss": loss_sum / total_weight,
            "grad_norm": gnorm_f,
            "update_successful": float(ok_b),
            "lr": float(host[3]),
            "n_mbs": float(mb_list.n_mbs),
            "n_tokens": float(total_weight),
            "step_time": step_time,
        }
        stats.update(self._perf_stats(input_, real_tokens, step_time))
        if not ok_b:
            logger.warning(
                f"non-finite grad norm {gnorm_f}; skipped optimizer step"
            )
        return stats

    def eval_batch(
        self,
        input_: TensorDict,
        loss_fn: Callable,
        loss_weight_fn: Callable,
        token_loss_fn: "TokenLossFn | None" = None,
    ) -> float | None:
        assert self.initialized
        _, packed_mbs, _ = self._prepare_mbs(input_)
        denom = sum(float(loss_weight_fn(p)) for p in packed_mbs)
        if self._use_fused_loss(token_loss_fn):
            key = ("eval_fused", token_loss_fn, self._vlm_grids)
            if key not in self._jit_cache:
                cfg, backend = self.model_config, self.config.backend

                def ev_fused(params, mb):
                    logp, ent = forward_fused_logp(
                        params, cfg, mb["input_ids"], mb["positions"],
                        mb["segment_ids"],
                        labels=jnp.roll(mb["input_ids"], shift=-1),
                        temperature=token_loss_fn.temperature,
                        need_entropy=token_loss_fn.needs_entropy,
                        chunk=backend.loss_chunk_size,
                        attn_spec=self.attn_spec,
                        pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                    )
                    return token_loss_fn.fn(logp, ent, mb)

                self._jit_cache[key] = jax.jit(ev_fused)
            evf = self._jit_cache[key]
            total = 0.0
            for packed in packed_mbs:
                total += float(evf(self.effective_params(), self._mb_to_device(packed)))
            return total / max(denom, 1.0)
        if pp_size(self.mesh) > 1:
            pkey = ("eval_pp", loss_fn, self._vlm_grids)
            if pkey not in self._jit_cache:
                cfg = self.model_config
                mesh, attn_spec = self.mesh, self.attn_spec
                vlm_grids = self._vlm_grids

                def ev_pp(params, mbs):
                    logits = forward_packed_pipelined(
                        params, cfg, mbs["input_ids"], mbs["positions"],
                        mbs["segment_ids"], mesh, attn_spec=attn_spec,
                        remat=False, vpp=self.config.backend.vpp,
                        pixel_values=mbs.get("pixel_values"),
                        image_grid_thw=vlm_grids,
                    )
                    return jnp.sum(jax.vmap(loss_fn)(logits, mbs))

                self._jit_cache[pkey] = jax.jit(ev_pp)
            mbs_dev = self._stacked_to_device(packed_mbs)
            total = float(self._jit_cache[pkey](self.effective_params(), mbs_dev))
            return total / max(denom, 1.0)
        key = ("eval", loss_fn, self._vlm_grids)
        if key not in self._jit_cache:
            cfg = self.model_config

            def ev(params, mb):
                logits = forward_packed(
                    params, cfg, mb["input_ids"], mb["positions"],
                    mb["segment_ids"], remat=False,
                    attn_spec=self.attn_spec,
                    pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                )
                return loss_fn(logits, mb)

            self._jit_cache[key] = jax.jit(ev)
        ev = self._jit_cache[key]
        total = 0.0
        for packed in packed_mbs:
            mb_dev = self._mb_to_device(packed)
            total += float(ev(self.effective_params(), mb_dev))
        return total / max(denom, 1.0)

    # --------------------------------------------------------------- forward

    def forward(
        self,
        input_: TensorDict,
        output_seqlens: list[int] | None = None,
        post_hook: Callable | None = None,
        aggregate_fn: Callable | None = None,
        logp_fused_temperature: float | None = None,
    ) -> Any:
        """Microbatched scoring forward (reference: base_hf_engine.py:513).

        ``post_hook(logits, mb) -> [T, ...]`` runs on-device per microbatch
        (e.g. gather_logprobs — never materialize full logits on host).
        Results are unpacked per sequence, restored to input row order, and
        re-padded to the input's [B, S] layout (pad = 0)."""
        assert self.initialized
        mb_list, packed_mbs, real_ns = self._prepare_mbs(input_)
        if pp_size(self.mesh) > 1:
            key = ("fwd_pp", post_hook, self._vlm_grids)
            if key not in self._jit_cache:
                cfg = self.model_config
                mesh, attn_spec = self.mesh, self.attn_spec
                vlm_grids = self._vlm_grids

                def fwd_pp(params, mbs):
                    logits = forward_packed_pipelined(
                        params, cfg, mbs["input_ids"], mbs["positions"],
                        mbs["segment_ids"], mesh, attn_spec=attn_spec,
                        remat=False, vpp=self.config.backend.vpp,
                        pixel_values=mbs.get("pixel_values"),
                        image_grid_thw=vlm_grids,
                    )
                    if post_hook is not None:
                        return jax.vmap(post_hook)(logits, mbs)
                    return logits

                self._jit_cache[key] = jax.jit(fwd_pp)
            mbs_dev = self._stacked_to_device(packed_mbs)
            stacked_out = np.asarray(
                jax.device_get(
                    self._jit_cache[key](self.effective_params(), mbs_dev)
                )
            )
            mb_outs = list(stacked_out)
        elif (
            logp_fused_temperature is not None
            and self.config.backend.loss_chunk_size > 0
            and not self.model_config.is_critic
        ):
            # chunked-fused scoring: next-token logp without [T, V] logits
            # (the compute_logp / recompute_logprob path must survive long
            # context just like the train step)
            key = ("fwd_fused", logp_fused_temperature, self._vlm_grids)
            if key not in self._jit_cache:
                cfg, backend = self.model_config, self.config.backend
                temp = logp_fused_temperature

                def fwd(params, mb):
                    logp, _ = forward_fused_logp(
                        params, cfg, mb["input_ids"], mb["positions"],
                        mb["segment_ids"],
                        labels=jnp.roll(mb["input_ids"], shift=-1),
                        temperature=temp,
                        chunk=backend.loss_chunk_size,
                        attn_spec=self.attn_spec,
                        pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                    )
                    return logp

                self._jit_cache[key] = jax.jit(
                    _retrace.wrap("train_engine.forward_fused", fwd)
                )
            fwd = self._jit_cache[key]
            mb_outs = None
        else:
            key = ("fwd", post_hook, self._vlm_grids)
            if key not in self._jit_cache:
                cfg = self.model_config

                def fwd(params, mb):
                    logits = forward_packed(
                        params, cfg, mb["input_ids"], mb["positions"],
                        mb["segment_ids"], remat=False,
                        attn_spec=self.attn_spec,
                        pixel_values=_flat_pixels(mb),
                        image_grid_thw=self._vlm_grids,
                    )
                    return (
                        post_hook(logits, mb) if post_hook is not None else logits
                    )

                self._jit_cache[key] = jax.jit(
                    _retrace.wrap("train_engine.forward", fwd)
                )
            fwd = self._jit_cache[key]
            mb_outs = None

        per_row: list[np.ndarray] = []
        for mb_idx, (packed, real_n) in enumerate(zip(packed_mbs, real_ns)):
            if mb_outs is not None:
                out = mb_outs[mb_idx][:real_n]
            else:
                mb_dev = self._mb_to_device(packed)
                out_dev = fwd(self.effective_params(), mb_dev)
                if distributed.process_count() > 1:
                    # the output token dim spans all hosts (process-order
                    # concat, like the input assembly); allgather and keep
                    # this host's segment — device_get alone cannot fetch
                    # non-addressable shards
                    t_local = int(packed["cu_seqlens"][-1])
                    full = distributed.gather_host_values(out_dev)
                    lo = distributed.process_index() * t_local
                    out = np.asarray(full)[lo : lo + t_local][:real_n]
                else:
                    out = np.asarray(jax.device_get(out_dev))[:real_n]
            if output_seqlens is not None:
                # per-sequence output lengths differ from input lengths
                # (reference base_hf_engine.py:516-544)
                rows_here = mb_list.forward_indices[mb_idx]
                out_lens = [output_seqlens[r] for r in rows_here]
                real_cu = np.concatenate([[0], np.cumsum(out_lens)]).astype(
                    np.int64
                )
                assert real_cu[-1] == real_n, (
                    f"output_seqlens sum {real_cu[-1]} != output tokens {real_n}"
                )
            else:
                cu = packed["cu_seqlens"]
                real_cu = cu[cu <= real_n]
            per_row.extend(unpack_sequence(out, real_cu))
        rows = mb_list.reorder_back(per_row)
        if aggregate_fn is not None:
            return aggregate_fn(rows)
        if output_seqlens is not None:
            return rows  # caller-defined lengths: return per-sequence arrays
        bs, s = np.asarray(input_["attention_mask"]).shape
        tail = rows[0].shape[1:] if rows and rows[0].ndim > 1 else ()
        padded = np.zeros((bs, s) + tail, dtype=rows[0].dtype if rows else np.float32)
        mask = np.asarray(input_["attention_mask"]).astype(bool)
        for i, r in enumerate(rows):
            idx = np.nonzero(mask[i])[0]
            padded[i, idx] = r
        return padded

    # ------------------------------------------------------------ checkpoint

    def _lora_adapter_path(self, path: str) -> str:
        return os.path.join(path, "lora_adapter.safetensors")

    def _save_lora_adapter(self, path: str):
        from safetensors.numpy import save_file

        flat = {}

        def walk(node, prefix):
            for k in sorted(node.keys()):
                v = node[k]
                name = f"{prefix}.{k}" if prefix else k
                if isinstance(v, dict):
                    walk(v, name)
                else:
                    flat[name] = np.ascontiguousarray(
                        np.asarray(jax.device_get(v))
                    )

        walk(self.lora_params, "")
        os.makedirs(path, exist_ok=True)
        save_file(flat, self._lora_adapter_path(path))

    def _load_lora_adapter(self, path: str):
        from safetensors.numpy import load_file

        flat = load_file(self._lora_adapter_path(path))
        tree: dict = {}
        for name, arr in flat.items():
            node = tree
            parts = name.split(".")
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = arr
        rep = NamedSharding(self.mesh, P())
        self.lora_params = jax.device_put(tree, rep)
        self._merged_cache = None

    def save(self, meta: SaveLoadMeta):
        if meta.weight_format == "hf":
            multi = distributed.process_count() > 1
            params = self.params
            opt_leaves = None
            if multi:
                # every host participates in the gathers (collectives!);
                # only host 0 writes files afterwards
                params = distributed.gather_host_values(params)
                if meta.with_optim:
                    opt_leaves = distributed.gather_host_values(
                        self._flat_opt_leaves()[0]
                    )
                if not distributed.is_main():
                    return
            hf_io.save_hf_params(params, self.model_config, meta.path)
            if self.config.lora is not None:
                # PEFT convention: frozen base + separate adapter file so a
                # resume restores the exact (base, adapter, optimizer) state
                self._save_lora_adapter(meta.path)
            if meta.tokenizer is not None:
                meta.tokenizer.save_pretrained(meta.path)
            if meta.with_optim:
                self._save_optimizer(
                    os.path.join(meta.path, "optim"), leaves=opt_leaves
                )
        elif meta.weight_format == "orbax":
            self._save_orbax(meta.path, with_optim=meta.with_optim)
        elif meta.weight_format == "sharded":
            self._save_sharded(meta.path, with_optim=meta.with_optim)
        else:
            raise ValueError(f"unknown weight_format {meta.weight_format}")

    def load(self, meta: SaveLoadMeta):
        if meta.weight_format == "hf":
            _, self.params = hf_io.load_hf_params(
                meta.path,
                self.model_config,
                dtype=self.config.backend.param_dtype,
                to_device=self._sharded_putter(self.param_shardings()),
            )
            self._merged_cache = None  # base changed; stale merge invalid
            if self.config.lora is not None and os.path.isfile(
                self._lora_adapter_path(meta.path)
            ):
                self._load_lora_adapter(meta.path)
            optim_dir = os.path.join(meta.path, "optim")
            if meta.with_optim and os.path.isdir(optim_dir):
                self._load_optimizer(optim_dir)
        elif meta.weight_format == "orbax":
            self._load_orbax(meta.path, with_optim=meta.with_optim)
        elif meta.weight_format == "sharded":
            self._load_sharded(meta.path, with_optim=meta.with_optim)
        else:
            raise ValueError(f"unknown weight_format {meta.weight_format}")

    def _flat_opt_leaves(self):
        leaves, treedef = jax.tree.flatten(self.opt_state)
        return leaves, treedef

    def _save_optimizer(self, path: str, leaves=None):
        os.makedirs(path, exist_ok=True)
        if leaves is None:
            leaves, _ = self._flat_opt_leaves()
        arrs = {
            f"leaf_{i}": np.asarray(jax.device_get(l)) for i, l in enumerate(leaves)
        }
        np.savez(os.path.join(path, "opt_state.npz"), step=self._opt_steps, **arrs)

    def _load_optimizer(self, path: str):
        data = np.load(os.path.join(path, "opt_state.npz"))
        leaves, treedef = self._flat_opt_leaves()
        new_leaves = []
        for i, old in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            if hasattr(old, "sharding"):
                new_leaves.append(
                    jax.device_put(arr.astype(old.dtype), old.sharding)
                )
            else:
                new_leaves.append(arr)
        self.opt_state = jax.tree.unflatten(treedef, new_leaves)
        self._opt_steps = int(data["step"])

    def _save_orbax(self, path: str, with_optim: bool):
        import orbax.checkpoint as ocp

        ckpt = {"params": self.params}
        if self.lora_params is not None:
            ckpt["lora_params"] = self.lora_params
        if with_optim:
            ckpt["opt_state"] = self.opt_state
            ckpt["opt_steps"] = self._opt_steps
        with ocp.StandardCheckpointer() as cp:
            cp.save(os.path.abspath(path), ckpt, force=True)

    def _load_orbax(self, path: str, with_optim: bool):
        import orbax.checkpoint as ocp

        target = {"params": self.params}
        if self.lora_params is not None:
            target["lora_params"] = self.lora_params
        if with_optim:
            target["opt_state"] = self.opt_state
            target["opt_steps"] = self._opt_steps
        with ocp.StandardCheckpointer() as cp:
            restored = cp.restore(os.path.abspath(path), target)
        self.params = restored["params"]
        if self.lora_params is not None:
            self.lora_params = restored["lora_params"]
        self._merged_cache = None
        if with_optim:
            self.opt_state = restored["opt_state"]
            self._opt_steps = int(restored["opt_steps"])

    # ------------------------------------------- topology-independent format

    @staticmethod
    def _spec_desc(leaf):
        """json-safe description of a leaf's partition spec (informational
        manifest metadata — restore derives its target shardings from ITS
        mesh, never from the saved one)."""
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None:
            return None
        return [list(p) if isinstance(p, (tuple, list)) else p for p in spec]

    @staticmethod
    def _nest(flat: dict) -> dict:
        tree: dict = {}
        for name, arr in flat.items():
            node = tree
            parts = name.split(".")
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = arr
        return tree

    def _save_sharded(self, path: str, with_optim: bool):
        """Manifest checkpoint (utils/checkpoint.py): one file per
        addressable shard plus per-shard digests, re-shardable into any
        mesh on restore. Leaf namespace: ``params.<dotted>``,
        ``lora.<dotted>``, ``opt.leaf_{i}``; opt step count rides the
        manifest extras."""
        from areal_tpu.utils import checkpoint as ckpt_fmt

        w = ckpt_fmt.CheckpointWriter(path)
        for name, leaf in self._walk_params(self.params):
            w.add_leaf(f"params.{name}", leaf, spec=self._spec_desc(leaf))
        if self.lora_params is not None:
            for name, leaf in self._walk_params(self.lora_params):
                w.add_leaf(f"lora.{name}", leaf, spec=self._spec_desc(leaf))
        extras = {}
        if with_optim:
            leaves, _ = self._flat_opt_leaves()
            for i, leaf in enumerate(leaves):
                w.add_leaf(f"opt.leaf_{i}", leaf, spec=self._spec_desc(leaf))
            extras["opt_steps"] = int(self._opt_steps)
        w.commit(extras=extras)

    def _load_sharded(self, path: str, with_optim: bool):
        """Restore a manifest checkpoint into THIS engine's mesh, whatever
        shape the saving mesh had. Digests verify before any weight
        loads; target shardings come from ``param_shardings()`` (params)
        and the freshly initialized opt_state (optimizer leaves), so an
        N-host checkpoint lands correctly on an M-host trainer."""
        from areal_tpu.utils import checkpoint as ckpt_fmt

        manifest = ckpt_fmt.read_manifest(path)
        shardings: dict = {}
        for name, sh in self._walk_params(self.param_shardings()):
            shardings[f"params.{name}"] = sh
        rep = NamedSharding(self.mesh, P())
        opt_leaves, opt_treedef = self._flat_opt_leaves()
        for i, old in enumerate(opt_leaves):
            sh = getattr(old, "sharding", None)
            # freshly initialized opt leaves can sit uncommitted on one
            # device; loading through that sharding would COMMIT them
            # there and clash with mesh-placed params inside jit — only
            # honor shardings that live on this engine's mesh
            if not (isinstance(sh, NamedSharding) and sh.mesh == self.mesh):
                sh = rep
            shardings[f"opt.leaf_{i}"] = sh
        for name in manifest["leaves"]:
            if name.startswith("lora."):
                shardings[name] = rep
        named, extras = ckpt_fmt.load_named(
            path, shardings=shardings, manifest=manifest
        )
        self.params = self._nest(
            {
                n[len("params."):]: a
                for n, a in named.items()
                if n.startswith("params.")
            }
        )
        lora = {
            n[len("lora."):]: a for n, a in named.items() if n.startswith("lora.")
        }
        if lora:
            self.lora_params = self._nest(lora)
        self._merged_cache = None
        if with_optim:
            new_leaves = []
            for i, old in enumerate(opt_leaves):
                arr = named.get(f"opt.leaf_{i}")
                if arr is None:
                    raise ValueError(
                        f"checkpoint at {path} has no opt.leaf_{i} — saved "
                        "without the optimizer, or the optimizer shape changed"
                    )
                new_leaves.append(arr)
            self.opt_state = jax.tree.unflatten(opt_treedef, new_leaves)
            self._opt_steps = int(extras.get("opt_steps", 0))

    # ---------------------------------------------------------- weight update

    def connect_engine(self, engine, meta: WeightUpdateMeta):
        """Pair with a rollout engine (reference: fsdp_engine.py:437-455)."""
        self._rollout_engine = engine
        self._weight_update_meta = meta

    def upload_weights(self, meta: WeightUpdateMeta):
        if meta.type == "disk":
            assert meta.path is not None
            params = self.effective_params()
            if distributed.process_count() > 1:
                # leaf-streamed: non-main hosts join each gather collective
                # but never hold more than one leaf in host RAM
                params = distributed.gather_tree_for_main(params)
                if not distributed.is_main():
                    return
            hf_io.save_hf_params(params, self.model_config, meta.path)
        elif meta.type in ("device", "http"):
            pass  # live handle / streamed by update_weights
        else:
            raise ValueError(f"unknown weight update type {meta.type}")

    @staticmethod
    def _walk_params(node, prefix=""):
        """Sorted dotted-path iteration over a params tree's leaves (the
        canonical wire order — see utils/wire.walk_named_leaves)."""
        from areal_tpu.utils.wire import walk_named_leaves

        yield from walk_named_leaves(node, prefix)

    @staticmethod
    def _leaf_digest(arr) -> bytes:
        """Exact content fingerprint of a materialized host leaf (shape and
        dtype are part of the identity — a reshaped same-bytes leaf must
        not pass as unchanged)."""
        import hashlib  # local: only the delta path pays the import

        h = hashlib.blake2b(digest_size=16)
        h.update(str(arr.dtype).encode())
        h.update(str(tuple(arr.shape)).encode())
        # uint8 view, not tobytes(): hashing in place avoids a transient
        # full-leaf byte copy per leaf per delta push
        h.update(np.ascontiguousarray(arr).view(np.uint8))
        return h.digest()

    @staticmethod
    def _leaf_local_digest(leaf) -> bytes:
        """Content fingerprint of THIS process's addressable shards of a
        (possibly cross-host sharded) leaf. Local-only on purpose:
        hashing needs host bytes, and gathering every leaf just to
        fingerprint it would cost the full-model gather delta sync
        exists to avoid. Each host only ever compares its own digests
        push-over-push; the cross-host ship decision is the allreduced
        OR of the per-host changed verdicts
        (:meth:`_multi_host_delta_plan`)."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(str(leaf.dtype).encode())
        h.update(str(tuple(leaf.shape)).encode())
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:  # plain host/np leaf
            h.update(np.ascontiguousarray(np.asarray(leaf)).view(np.uint8))
            return h.digest()
        # shard.index is a tuple of slices (not orderable); its repr is a
        # deterministic sort key, and replica_id breaks replication ties
        for s in sorted(
            shards, key=lambda s: (str(s.index), s.replica_id)
        ):
            arr = np.ascontiguousarray(np.asarray(s.data))
            h.update(arr.view(np.uint8))
        return h.digest()

    def _multi_host_delta_plan(self, target) -> tuple[set[str], dict]:
        """Cross-host agreement on WHICH leaves a multi-host delta push
        ships. Every trainer process walks the (structurally identical)
        params tree in the same sorted order, digests its LOCAL shard
        bytes per leaf, and contributes one changed bit; the head
        contributes a RESET bit (the client's server set — which only it
        sees — changed, voiding the delta baseline). ONE
        ``sync_max_vector`` collective merges the bitmap: a leaf ships
        if ANY host saw its shard change, and the reset bit forces a
        full re-ship everywhere. Because the merged vector is identical
        on every host, the per-leaf gather collectives inside the chunk
        stream can never diverge — the loud error below fires only on
        genuine post-broadcast disagreement (a diverged params tree or a
        broken collective), never on ordinary sharded updates.

        Returns ``(ship_paths, new_local_fingerprints)``; the caller
        commits the fingerprints only after the push succeeds. (A failed
        head push with already-updated spectator fingerprints is SAFE
        here, unlike under per-host decisions: the head's changed bits
        force the re-ship through the OR.)"""
        import hashlib

        # reconcile the PREVIOUS push's outcome first: spectators stashed
        # their fingerprint updates (only the head observed whether that
        # stream completed); one broadcast applies or discards the stash,
        # so a head-failed push re-ships a leaf even when it changed only
        # on a spectator's shard. All hosts are aligned here (entering
        # update_weights together), so the collective cannot mismatch.
        last_ok = distributed.broadcast_obj(
            self._last_delta_push_ok if distributed.is_main() else None
        )
        pending, self._pending_wire_fp = self._pending_wire_fp, None
        if not distributed.is_main() and pending is not None and last_ok:
            self._wire_fingerprints.update(pending)
        # armed for THIS push: an exception before the head's post-push
        # commit leaves it False, and the next plan discards the stashes
        self._last_delta_push_ok = False

        paths: list[str] = []
        local_digests: dict[str, bytes] = {}
        changed: list[int] = []
        for path, leaf in self._walk_params(self.effective_params()):
            digest = self._leaf_local_digest(leaf)
            paths.append(path)
            local_digests[path] = digest
            changed.append(
                0 if self._wire_fingerprints.get(path) == digest else 1
            )
        reset = 0
        if distributed.is_main():
            addrs = tuple(sorted(getattr(target, "addresses", ()) or ()))
            if addrs != self._wire_fp_addrs:
                if self._wire_fp_addrs is not None:
                    logger.info(
                        "delta weight sync: server set changed; forcing a "
                        "full re-ship"
                    )
                reset = 1
                self._wire_fp_addrs = addrs
        vec = np.asarray(changed + [reset], np.int64)
        merged = distributed.sync_max_vector(vec, len(vec))
        reset = bool(merged[-1])
        ship = (
            set(paths)
            if reset
            else {p for p, m in zip(paths, merged[:-1]) if m}
        )
        # post-broadcast verification: every host must now hold the SAME
        # plan over the SAME leaf order, or the skip decisions would
        # silently diverge mid-stream. This is the only condition that
        # still raises on a multi-host delta push.
        plan_digest = hashlib.blake2b(
            "\n".join(paths).encode()
            + b"|"
            + merged.astype(np.int64).tobytes(),
            digest_size=16,
        ).hexdigest()
        head_digest = distributed.broadcast_obj(
            plan_digest if distributed.is_main() else None
        )
        if head_digest != plan_digest:
            raise RuntimeError(
                "multi-host delta weight sync: plan disagreement after "
                f"broadcast (host {distributed.process_index()} computed "
                f"{plan_digest}, head broadcast {head_digest}) — the "
                "params trees or collectives have diverged; aborting "
                "before a mixed stream can ship"
            )
        if reset:
            self._wire_fingerprints.clear()
        logger.info(
            "multi-host delta plan: %d/%d leaves ship%s",
            len(ship), len(paths), " (reset)" if reset else "",
        )
        return ship, local_digests

    def _chunked(self, chunk_mb: int, materialize, skip=None):
        """Group leaves into <= chunk_mb chunks (oversized single leaves
        go alone); ``materialize(leaf) -> array`` picks host vs device;
        ``skip(path, arr) -> bool`` drops a leaf from the wire (delta
        sync). If EVERY leaf is skipped, the smallest one ships anyway —
        the version-bump protocol needs at least one chunk to commit."""
        budget = chunk_mb * 1_000_000
        cur: dict = {}
        size = 0
        skipped = 0
        smallest = None  # (nbytes, path, arr) fallback for all-skipped
        shipped_any = False
        t_chunk = time.perf_counter()
        for path, leaf in self._walk_params(self.effective_params()):
            arr = materialize(leaf)
            nbytes = int(getattr(arr, "nbytes", arr.size * arr.dtype.itemsize))
            if skip is not None and skip(path, arr):
                skipped += 1
                if smallest is None or nbytes < smallest[0]:
                    smallest = (nbytes, path, arr)
                continue
            shipped_any = True
            if cur and size + nbytes > budget:
                stats_tracker.DEFAULT_TRACKER.scalar(
                    **{"time_perf/weight_sync_gather": (
                        time.perf_counter() - t_chunk
                    )}
                )
                yield cur
                cur, size = {}, 0
                t_chunk = time.perf_counter()
            cur[path] = arr
            size += nbytes
        if not shipped_any and smallest is not None:
            # nothing changed since the last push: ship the smallest leaf so
            # the final-chunk commit still bumps every server's version
            cur[smallest[1]] = smallest[2]
        if skipped:
            logger.info(
                "delta weight sync: skipped %d unchanged leaves", skipped
            )
        if cur:
            stats_tracker.DEFAULT_TRACKER.scalar(
                **{"time_perf/weight_sync_gather": (
                    time.perf_counter() - t_chunk
                )}
            )
            yield cur

    def _weight_chunks(
        self,
        chunk_mb: int,
        wire_dtype: str | None = None,
        delta_only: bool = False,
        new_fingerprints: dict | None = None,
        ship_paths: set | None = None,
    ):
        """Yield dotted-path-named host-array chunks of <= chunk_mb MB
        each. The staging buffer holds one chunk at a time, bounding host
        RAM like the reference's weight_chunked_mem_mb bucketing
        (fsdp_engine.py:359-401). ``wire_dtype`` casts each leaf ON DEVICE
        before the host gather (bf16 halves the wire bytes of an
        fp32-trained model); ``delta_only`` skips leaves whose content
        digest matches the last successful push (``new_fingerprints``
        collects this push's digests — the caller commits them into
        ``self._wire_fingerprints`` only after the push succeeds)."""
        multi = distributed.process_count() > 1
        wire = _DTYPES[wire_dtype] if wire_dtype else None

        def materialize(leaf):
            if wire is not None and leaf.dtype != wire:
                leaf = leaf.astype(wire)  # device-side cast, XLA-fused
            if multi:
                # cross-host sharded leaf: every host joins the gather (a
                # collective) even though only host 0 pushes the chunks
                return distributed.gather_host_values(leaf)
            return np.asarray(jax.device_get(leaf))

        skip = None
        if delta_only and ship_paths is not None:
            # multi-host: the ship set was agreed by the allreduced plan
            # (one bitmap collective) BEFORE the stream, so every host
            # skips identically — materialize still runs per leaf on
            # every host, keeping the gather collectives aligned
            def skip(path, arr):
                return path not in ship_paths

        elif delta_only:
            fingerprints = self._wire_fingerprints

            def skip(path, arr):
                digest = self._leaf_digest(arr)
                if new_fingerprints is not None:
                    new_fingerprints[path] = digest
                return fingerprints.get(path) == digest

        yield from self._chunked(chunk_mb, materialize, skip=skip)

    def _weight_chunks_device(
        self, chunk_mb: int, wire_dtype: str | None = None
    ):
        """Like :meth:`_weight_chunks` but yields LIVE device arrays (no
        host gather): the device-transfer path ships buffers
        device-to-device, so pulling them through host numpy would defeat
        the point. Leaves stay in their training sharding; the client
        gathers each chunk single-shard on device. No delta support here —
        exact fingerprints need host bytes this path exists to avoid."""
        wire = _DTYPES[wire_dtype] if wire_dtype else None

        def materialize(leaf):
            if wire is not None and leaf.dtype != wire:
                return leaf.astype(wire)
            return leaf

        yield from self._chunked(chunk_mb, materialize)

    def update_weights(self, meta: WeightUpdateMeta | None = None):
        """Push current weights to the paired rollout engine and bump
        versions on both sides (reference train loop: gsm8k_grpo.py:196-255).

        type="device" + a colocated engine => direct HBM array re-placement
        (the reference's NCCL-broadcast fast path, SURVEY §3.3, without the
        process-group machinery); type="disk" => safetensors + fan-out."""
        meta = meta or self._weight_update_meta
        assert meta is not None, "call connect_engine first or pass meta"
        if (meta.delta_only or meta.wire_dtype) and meta.type not in (
            "http", "shm", "device_transfer"
        ):
            # loud, not silent: the knobs only exist on the streamed
            # paths — a disk/device/lora push would ship full-size,
            # full-dtype with no signal otherwise
            raise NotImplementedError(
                "wire_dtype/delta_only apply to the streamed weight-update "
                f"paths (http/shm/device_transfer), not type={meta.type!r}"
            )
        next_version = self.get_version() + 1
        if meta.type == "device":
            target = self._rollout_engine
            assert target is not None and hasattr(
                target, "update_weights_from_arrays"
            ), "device weight updates need a colocated engine (LocalInfEngine)"
            target.update_weights_from_arrays(
                self.effective_params(), next_version
            )
        elif meta.type in ("http", "shm"):
            target = self._rollout_engine
            method = (
                "update_weights_from_tensors"
                if meta.type == "http"
                else "update_weights_from_shm"
            )
            assert target is not None and hasattr(target, method), (
                f"{meta.type} weight updates need a RemoteInfEngine"
            )
            ship_paths: set | None = None
            new_fp: dict[str, bytes] = {}
            if meta.delta_only and distributed.process_count() > 1:
                # multi-host delta: the full-re-ship reset keys off the
                # CLIENT's server list, which only the rollout head sees —
                # so the per-leaf ship decision (one changed-bitmap
                # allreduce + the head's reset bit) is agreed across
                # hosts BEFORE the stream; only post-broadcast
                # disagreement raises (inside the plan)
                ship_paths, new_fp = self._multi_host_delta_plan(target)
            elif meta.delta_only:
                # a changed server set (scale-up, replacement node) voids
                # the delta baseline: a fresh server holds none of the
                # previously-shipped leaves, so ship everything once
                addrs = tuple(sorted(getattr(target, "addresses", ()) or ()))
                if addrs != self._wire_fp_addrs:
                    if self._wire_fp_addrs is not None:
                        logger.info(
                            "delta weight sync: server set changed; "
                            "forcing a full re-ship"
                        )
                    self._wire_fingerprints.clear()
                    self._wire_fp_addrs = addrs
            chunks = self._weight_chunks(
                meta.chunked_mem_mb,
                wire_dtype=meta.wire_dtype,
                delta_only=meta.delta_only,
                new_fingerprints=new_fp,
                ship_paths=ship_paths,
            )
            if distributed.process_count() > 1 and not distributed.is_main():
                for _ in chunks:  # join the per-leaf gather collectives
                    pass
            elif meta.delta_only and self._wire_fingerprints:
                # the stream only carries changed leaves: stamp the base
                # version so a server not exactly there (silent restart at
                # the same address) refuses instead of committing a mixed
                # tree (it then rejoins via the disk re-push)
                getattr(target, method)(
                    chunks, next_version,
                    delta_base_version=next_version - 1,
                )
            else:
                getattr(target, method)(chunks, next_version)
            if meta.delta_only:
                if (
                    distributed.process_count() > 1
                    and not distributed.is_main()
                ):
                    # a spectator never learns THIS push's outcome (only
                    # the head pushes): committing digests here after a
                    # head-side failure would make a leaf changed only on
                    # this host's shard read as unchanged on the retry —
                    # a silently mixed tree on the servers. Stash instead;
                    # the next plan's outcome broadcast applies or
                    # discards the stash.
                    self._pending_wire_fp = new_fp
                else:
                    # only after the push SUCCEEDED: a failed push must
                    # re-ship these leaves next time (quarantined servers
                    # rejoin via the version-checked disk re-push, not
                    # via deltas)
                    self._wire_fingerprints.update(new_fp)
                    self._last_delta_push_ok = True
        elif meta.type == "device_transfer":
            # cross-process DEVICE-PATH resync: servers pull staged
            # buffers from this process's transfer server directly into
            # their device memory (the reference's dedicated NCCL
            # broadcast group, fsdp_engine.py:359-401) — no host-RAM or
            # HTTP-body staging of the payload. "Cross-host" here means
            # trainer host vs SERVER hosts; a multi-PROCESS trainer would
            # need a pre-gather of its non-addressable leaves (use the
            # http/shm path there until wired)
            if distributed.process_count() > 1:
                raise NotImplementedError(
                    "device_transfer weight updates from a multi-process "
                    "trainer are not wired (leaves are not fully "
                    "addressable per process); use type='http' or 'shm'"
                )
            if meta.delta_only:
                # loud, not silent: exact fingerprints need the host bytes
                # this path exists to avoid — a user who set the knob must
                # not believe they are getting delta sync
                raise NotImplementedError(
                    "delta_only is not supported on the device_transfer "
                    "path (no host bytes to fingerprint exactly); use "
                    "type='http' or 'shm'"
                )
            target = self._rollout_engine
            assert target is not None and hasattr(
                target, "update_weights_from_device_transfer"
            ), "device_transfer weight updates need a RemoteInfEngine"
            target.update_weights_from_device_transfer(
                self._weight_chunks_device(
                    meta.chunked_mem_mb, wire_dtype=meta.wire_dtype
                ),
                next_version,
            )
        elif meta.type == "lora":
            # adapter-native sync: ship ONLY the rank-r factors (megabytes)
            # and let the serving side merge against its retained base —
            # the reference's SGLang adapter hot-swap
            # (areal/engine/sglang_remote.py:82-106)
            lora_cfg = self.config.lora
            assert lora_cfg is not None, (
                "weight_update type 'lora' needs a LoRA-configured engine"
            )
            target = self._rollout_engine
            assert target is not None and hasattr(
                target, "update_lora_weights"
            ), "lora weight updates need an engine with update_lora_weights"
            named: dict[str, np.ndarray] = {}
            for k in sorted(self.lora_params["layers"].keys()):
                leaf = self.lora_params["layers"][k]
                if distributed.process_count() > 1:
                    named[f"layers.{k}"] = distributed.gather_host_values(leaf)
                else:
                    named[f"layers.{k}"] = np.asarray(jax.device_get(leaf))
            if distributed.process_count() > 1 and not distributed.is_main():
                pass  # joined the gathers above; host 0 pushes
            else:
                target.update_lora_weights(
                    named, lora_cfg.alpha / lora_cfg.rank, next_version
                )
        else:
            self.upload_weights(meta)
            if self._rollout_engine is not None:
                self._rollout_engine.update_weights(meta)
        self.set_version(next_version)
        if self._rollout_engine is not None:
            self._rollout_engine.set_version(next_version)
