"""Reward-model training engine: pairwise Bradley-Terry loss.

Behavior parity with the reference's RW engine (areal/engine/rw/rw_engine.py,
exercised by examples/alignment/hhrlhf_rw.py): the model is the decoder with
a scalar value head (is_critic=True); each training pair is two adjacent rows
(even index = chosen, odd = rejected); the score of a sequence is the value
at its final token; loss = -log sigmoid(score_chosen - score_rejected).

The packed formulation stays jit-static: per-sequence scores come from a
``segment_sum`` of last-token-masked values with ``num_segments=T`` (an upper
bound), so no dynamic gathers appear in the graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import TrainEngineConfig
from areal_tpu.engine.train_engine import TPUTrainEngine
from areal_tpu.utils.data import TensorDict


def _identity_hook(values, mb):
    """Module-level (stable identity => engine.forward jit-cache hit)."""
    return values


def rw_pairwise_loss_fn(values: jnp.ndarray, input_data) -> jnp.ndarray:
    """SUM-reduced pairwise loss over the microbatch's (chosen, rejected)
    pairs. ``values`` [T] from the critic-headed forward."""
    seg = input_data["segment_ids"]  # [T], alignment-pad carries its own id
    t = seg.shape[0]
    nxt = jnp.concatenate([seg[1:], jnp.full((1,), -3, seg.dtype)])
    is_last = (seg != nxt) & (seg >= 0)
    scores = jax.ops.segment_sum(
        jnp.where(is_last, values, 0.0), jnp.clip(seg, 0, t - 1), num_segments=t
    )  # [T]; entry s = last-token value of sequence s (packed order)
    n_seqs = input_data["pair_mask"].shape[0]
    s = scores[:n_seqs].reshape(-1, 2)  # [n_pairs, 2] chosen|rejected
    pair_ok = input_data["pair_mask"].reshape(-1, 2)[:, 0].astype(bool)
    margin = s[:, 0] - s[:, 1]
    loss = -jax.nn.log_sigmoid(margin)
    return jnp.sum(jnp.where(pair_ok, loss, 0.0))


class RWEngine:
    """Algorithm wrapper (reference RWEngine pattern)."""

    def __init__(self, engine: TPUTrainEngine):
        self.engine = engine

    def train_rm(self, data: TensorDict) -> dict[str, float]:
        """``data``: padded batch where rows 2i / 2i+1 are the chosen /
        rejected completions of pair i (input_ids + attention_mask [+
        loss_mask])."""
        bs = np.asarray(data["attention_mask"]).shape[0]
        assert bs % 2 == 0, "reward-model batches are (chosen, rejected) pairs"
        lens = np.asarray(data["attention_mask"]).sum(-1)
        pair_lens = lens.reshape(-1, 2).sum(-1)
        budget = self.engine.config.mb_spec.max_tokens_per_mb
        if pair_lens.max() > budget:
            raise ValueError(
                f"a (chosen, rejected) pair spans {int(pair_lens.max())} tokens "
                f"> max_tokens_per_mb={budget}; pairs cannot split across "
                "microbatches — raise mb_spec.max_tokens_per_mb or lower the "
                "dataset max_length (<= budget // 2)"
            )
        data = dict(data)
        data["pair_mask"] = np.ones(bs, np.int64)
        self.engine.train()
        return self.engine.train_batch(
            data,
            loss_fn=rw_pairwise_loss_fn,
            loss_weight_fn=lambda x: len(np.asarray(x["pair_mask"])) // 2,
            group_size=2,  # a pair never splits across microbatches
        )

    def score(self, data: TensorDict) -> np.ndarray:
        """Per-sequence scalar scores (value at each sequence's last token),
        shape [B]."""
        self.engine.train(False)
        vals = self.engine.forward(input_=data, post_hook=_identity_hook)
        vals = np.asarray(vals)  # padded [B, S]
        lens = np.asarray(data["attention_mask"]).sum(-1).astype(int)
        return vals[np.arange(len(lens)), lens - 1]


class TPURWEngine(TPUTrainEngine):
    """Engine-fused variant (reference FSDPRWEngine pattern)."""

    def __init__(self, config: TrainEngineConfig):
        super().__init__(config)
        self.rw = RWEngine(self)

    def train_rm(self, data: TensorDict) -> dict[str, float]:
        return self.rw.train_rm(data)

    def score(self, data: TensorDict) -> np.ndarray:
        return self.rw.score(data)
