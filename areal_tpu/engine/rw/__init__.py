from areal_tpu.engine.rw.rw_engine import RWEngine, TPURWEngine  # noqa: F401
