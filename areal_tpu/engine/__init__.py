from areal_tpu.engine.train_engine import TPUTrainEngine

__all__ = ["TPUTrainEngine"]
