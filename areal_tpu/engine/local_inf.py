"""Colocated in-process inference engine.

The reference's colocated mode runs SGLang inside the trainer process
(areal/experimental/sglang_engine.py:40, allocation ``jaxgen:..|gspmd:..``);
here the :class:`GenerationEngine` shares the chip with the train engine and
weight updates are direct HBM-local array re-placements
(``update_weights_from_arrays``) — no HTTP, no disk, no NCCL group.

Implements the same ``InferenceEngine`` surface as the remote client, so
workflows and training scripts are identical across colocated/disaggregated
allocation modes.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable

from areal_tpu.api.cli_args import InferenceEngineConfig, JaxGenConfig
from areal_tpu.api.engine_api import InferenceEngine
from areal_tpu.api.io_struct import ModelRequest, ModelResponse, WeightUpdateMeta
from areal_tpu.core.workflow_executor import WorkflowExecutor
from areal_tpu.inference.engine import GenerationEngine
from areal_tpu.utils import logging

logger = logging.getLogger("LocalInfEngine")


class LocalInfEngine(InferenceEngine):
    def __init__(
        self,
        config: InferenceEngineConfig,
        gen_config: JaxGenConfig,
        model_config=None,
        params=None,
        tokenizer=None,
    ):
        self.config = config
        self.engine = GenerationEngine(
            gen_config, model_config=model_config, params=params, tokenizer=tokenizer
        )
        self.executor = WorkflowExecutor(config, self)

    def initialize(self, addr: str | None = None, train_data_parallel_size: int | None = None):
        self.engine.start()
        self.executor.initialize(train_data_parallel_size)

    def destroy(self):
        self.executor.destroy()
        self.engine.stop()

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_done(resp: ModelResponse):
            loop.call_soon_threadsafe(
                lambda: fut.set_result(resp) if not fut.done() else None
            )

        self.engine.submit(
            req.rid, list(req.input_ids), req.gconfig, on_done,
            image_data=req.image_data,
        )
        resp = await fut
        # colocated pause aborts like the remote path; splice by re-issuing
        if resp.stop_reason == "abort" and len(resp.output_tokens) < req.gconfig.max_new_tokens:
            while self.engine._paused.is_set():
                await asyncio.sleep(0.05)
            rest = await self.agenerate(
                ModelRequest(
                    rid=req.rid,
                    input_ids=list(req.input_ids) + resp.output_tokens,
                    gconfig=req.gconfig.new(
                        max_new_tokens=req.gconfig.max_new_tokens
                        - len(resp.output_tokens)
                    ),
                    tokenizer=req.tokenizer,
                    image_data=req.image_data,
                )
            )
            return ModelResponse(
                input_tokens=list(req.input_ids),
                output_tokens=resp.output_tokens + rest.output_tokens,
                output_logprobs=resp.output_logprobs + rest.output_logprobs,
                output_versions=resp.output_versions + rest.output_versions,
                stop_reason=rest.stop_reason,
                latency=resp.latency + rest.latency,
                ttft=resp.ttft,
                itl=resp.itl + rest.itl,
                tokenizer=req.tokenizer,
            )
        return resp

    def generate(self, req: ModelRequest) -> ModelResponse:
        return asyncio.run(self.agenerate(req))

    # -- weight updates -------------------------------------------------

    def update_weights(self, meta: WeightUpdateMeta):
        if meta.type == "disk":
            assert meta.path is not None
            self.engine.update_weights_from_disk(meta.path)
        else:
            raise ValueError(
                "device updates go through update_weights_from_arrays "
                "(driven by TPUTrainEngine.update_weights)"
            )

    def update_weights_from_arrays(self, params, version: int | None = None):
        self.engine.update_weights_from_arrays(params, version)

    def update_lora_weights(
        self, named: dict, scale: float, next_version: int
    ):
        """Colocated adapter-only sync (same surface as RemoteInfEngine)."""
        self.engine.update_lora_from_named_arrays(named, scale, next_version)

    def get_version(self) -> int:
        return self.engine.get_version()

    def set_version(self, version: int):
        self.engine.set_version(version)

    # -- rollout runtime ------------------------------------------------

    def submit(self, data, workflow=None, workflow_builder: Callable | None = None):
        self.executor.submit(data, workflow, workflow_builder)

    def wait(self, count: int, timeout: float | None = None):
        return self.executor.wait(count, timeout=timeout)

    def rollout_batch(self, data: list[Any], workflow=None, workflow_builder=None):
        return self.executor.rollout_batch(data, workflow, workflow_builder)

    def prepare_batch(self, dataloader, workflow=None, workflow_builder=None):
        return self.executor.prepare_batch(dataloader, workflow, workflow_builder)

    def pause(self):
        self.engine.pause()
        self.executor.pause()

    def resume(self):
        self.engine.resume()
        self.executor.resume()
