from areal_tpu.engine.ppo.actor import PPOActor, TPUPPOActor
from areal_tpu.engine.ppo.critic import PPOCritic, TPUPPOCritic

__all__ = ["PPOActor", "TPUPPOActor", "PPOCritic", "TPUPPOCritic"]
