"""PPO critic: value scoring + clipped value-loss update.

Behavior parity with the reference's ``areal/engine/ppo/critic.py``
(PPOCritic/FSDPPPOCritic). The critic model is the same decoder with a
scalar value head (TransformerConfig.is_critic=True -> forward_packed
returns values [T]).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import PPOCriticConfig
from areal_tpu.engine.train_engine import TokenLossFn, TPUTrainEngine
from areal_tpu.utils.data import TensorDict, split_padded_tensor_dict_into_mb_list
from areal_tpu.utils.functional import ppo_critic_loss_fn


class PPOCritic:
    def __init__(self, config: PPOCriticConfig, engine: TPUTrainEngine):
        self.config = config
        self.engine = engine
        self._loss_fn = functools.partial(
            critic_loss_fn,
            value_eps_clip=config.value_eps_clip,
            loss_fn_type=config.value_loss_type,
            huber_delta=config.huber_delta,
        )
        # value-head twin of the fused-loss contract: lets the 1F1B
        # pipeline schedule drive critics (values [T] in place of logp)
        self._token_loss_fn = TokenLossFn(
            fn=functools.partial(_value_token_loss, loss_fn=self._loss_fn),
            is_value=True,
        )

    def compute_values(self, data: TensorDict) -> np.ndarray:
        """Value of every token position, padded [B, S]."""
        from areal_tpu.engine.train_engine import FORWARD_INPUT_KEYS

        self.engine.train(False)
        # forward consumes only the model inputs; per-host-different extras
        # (rewards etc.) must not hit the replicated device_put branch
        return self.engine.forward(
            input_={
                k: v for k, v in data.items() if k in FORWARD_INPUT_KEYS
            },
            post_hook=_take_values,
        )

    def ppo_update(self, data: TensorDict) -> list[dict[str, float]]:
        data = dict(data)
        for key in ["rewards", "tot_rewards", "kl_rewards", "versions"]:
            data.pop(key, None)
        self.engine.train()
        mb_inputs = split_padded_tensor_dict_into_mb_list(
            data,
            max_tokens_per_mb=1 << 30,
            min_n_mbs=self.config.ppo_n_minibatches,
        )
        all_stats = []
        for mb in mb_inputs.mbs:
            stat = self.engine.train_batch(
                mb,
                loss_fn=self._loss_fn,
                loss_weight_fn=lambda x: np.asarray(x["loss_mask"]).sum(),
                token_loss_fn=self._token_loss_fn,
            )
            all_stats.append(stat)
        return all_stats


class TPUPPOCritic(TPUTrainEngine):
    """Engine-fused critic (reference FSDPPPOCritic pattern)."""

    def __init__(self, config: PPOCriticConfig):
        super().__init__(config)
        self.critic = PPOCritic(config, self)

    def compute_values(self, *args, **kwargs):
        return self.critic.compute_values(*args, **kwargs)

    def ppo_update(self, *args, **kwargs):
        return self.critic.ppo_update(*args, **kwargs)


def _take_values(values, input_data):
    return values


def _value_token_loss(values, _entropy, input_data, loss_fn):
    """TokenLossFn.is_value adapter: (values [T], zeros, mb) -> sum loss."""
    return loss_fn(values, input_data)


def critic_loss_fn(
    values: jnp.ndarray,
    input_data,
    value_eps_clip: float,
    loss_fn_type: str,
    huber_delta: float,
):
    """SUM-reduced clipped value loss over valid tokens."""
    loss, _ = ppo_critic_loss_fn(
        value=values,
        old_value=input_data["values"],
        target_value=input_data["returns"],
        value_eps_clip=value_eps_clip,
        loss_mask=input_data["loss_mask"],
        loss_fn_type=loss_fn_type,
        huber_delta=huber_delta,
    )
    count = jnp.maximum(jnp.sum(input_data["loss_mask"].astype(bool)), 1)
    return loss * count
