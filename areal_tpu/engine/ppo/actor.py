"""PPO/GRPO actor: advantage pipeline + policy update orchestration.

Behavior parity with the reference's ``areal/engine/ppo/actor.py``
(PPOActor:25, FSDPPPOActor:278): the advantage math (reward shaping, KL
regularization, masked GAE, normalization) follows compute_advantages
(actor.py:72-164) token for token; the update path follows ppo_update
(actor.py:166-275) including dynamic sampling and minibatch splitting.

TPU-native differences: GAE runs as a reverse ``lax.scan`` on-device
(the cuGAE equivalent, csrc/cugae/gae.cu:10-28); per-token training stats
are computed host-side around the jitted loss rather than inside it.
"""

from __future__ import annotations

import functools
from typing import Any

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import MicroBatchSpec, NormConfig, PPOActorConfig
from areal_tpu.engine.train_engine import TokenLossFn, TPUTrainEngine
from areal_tpu.utils import stats_tracker
from areal_tpu.utils.data import (
    KLEstimator,
    Normalization,
    TensorDict,
    split_padded_tensor_dict_into_mb_list,
)
from areal_tpu.utils.functional import (
    dynamic_sampling,
    gae_padded,
    gather_logprobs,
    gather_logprobs_entropy,
    ppo_actor_loss_fn,
    reward_overlong_penalty,
)


class PPOActor:
    """Algorithm wrapper over a TrainEngine (reference actor.py:25)."""

    def __init__(self, config: PPOActorConfig, engine: TPUTrainEngine):
        self.config = config
        self.engine = engine
        self.temperature = config.temperature
        self.reward_bias = config.reward_bias
        self.reward_scaling = config.reward_scaling
        self.reward_clip = config.reward_clip
        self.kl_ctl = config.kl_ctl
        self.kl_estimator = KLEstimator(config.kl_estimator)
        self.discount = config.discount
        self.gae_lambda = config.gae_lambda
        self.mask_no_eos_with_zero = config.mask_no_eos_with_zero
        self.dynamic_sampling = config.dynamic_sampling
        self.group_size = config.group_size
        # RL training-health observatory (utils/rl_health.py): attached by
        # the trainer entry point when rl_health.enabled; None costs only
        # `is not None` checks on the update path (code-inspection pinned)
        self.rl_health = None

        if config.reward_norm is not None:
            # full spec (reference PPOActorConfig.reward_norm); a
            # group-level norm without an explicit group_size (NormConfig
            # default 1 = every sample its own group -> all-zero rewards)
            # means the actor's GRPO group
            rn = config.reward_norm
            rn_group = rn.group_size
            if rn_group <= 1 and "group" in (rn.mean_level, rn.std_level):
                rn_group = config.group_size
            self.reward_norm = Normalization(
                mean_level=rn.mean_level,
                std_level=rn.std_level,
                group_size=rn_group,
                eps=rn.eps,
                mean_leave1out=rn.mean_leave1out,
                std_unbiased=rn.std_unbiased,
            )
        elif config.group_reward_norm:  # boolean shorthand for group/group
            self.reward_norm = Normalization(
                mean_level="group",
                std_level="group",
                group_size=config.group_size,
            )
        else:
            self.reward_norm = None
        self.adv_norm = (
            Normalization(
                mean_level=config.adv_norm.mean_level,
                std_level=config.adv_norm.std_level,
                group_size=config.adv_norm.group_size,
                eps=config.adv_norm.eps,
                mean_leave1out=config.adv_norm.mean_leave1out,
                std_unbiased=config.adv_norm.std_unbiased,
            )
            if config.adv_norm is not None
            else None
        )
        # stable hook identity => jit cache hit in engine.forward
        self._logp_hook = functools.partial(
            _calc_logprobs, temperature=self.temperature
        )
        self._loss_fn = functools.partial(
            grpo_loss_fn,
            temperature=self.temperature,
            eps_clip=config.eps_clip,
            eps_clip_higher=config.eps_clip_higher,
            c_clip=config.c_clip,
            behav_imp_weight_cap=config.behav_imp_weight_cap,
            entropy_coeff=config.entropy_coeff,
            entropy_clamp=config.entropy_clamp,
        )
        # fused chunked-LM-head twin (used when backend.loss_chunk_size > 0)
        self._token_loss_fn = TokenLossFn(
            fn=functools.partial(
                grpo_loss_from_logp,
                eps_clip=config.eps_clip,
                eps_clip_higher=config.eps_clip_higher,
                c_clip=config.c_clip,
                behav_imp_weight_cap=config.behav_imp_weight_cap,
                entropy_coeff=config.entropy_coeff,
                entropy_clamp=config.entropy_clamp,
            ),
            temperature=self.temperature,
            needs_entropy=config.entropy_coeff != 0.0,
        )

    def compute_logp(self, data: TensorDict) -> np.ndarray:
        """Teacher-forced logprobs of the batch under current weights,
        next-token convention (index t scores token t+1). Padded [B, S].
        Only the model-input keys go through (FORWARD_INPUT_KEYS): per-host
        -different extras must not hit the replicated device_put branch."""
        from areal_tpu.engine.train_engine import FORWARD_INPUT_KEYS

        self.engine.train(False)
        return self.engine.forward(
            input_={k: v for k, v in data.items() if k in FORWARD_INPUT_KEYS},
            post_hook=self._logp_hook,
            logp_fused_temperature=self.temperature,
        )

    def compute_advantages(self, data: TensorDict) -> None:
        """In-place advantage pipeline (reference actor.py:72-164)."""
        cfg = self.config
        input_ids = np.asarray(data["input_ids"])
        bs, max_seqlen = input_ids.shape
        batch_idx = np.arange(bs)

        if cfg.overlong_reward_penalty:
            data = reward_overlong_penalty(
                data,
                overlong_tokens=cfg.overlong_tokens,
                overlong_penalty_factor=cfg.overlong_penalty_factor,
                max_response_length=cfg.max_new_tokens,
            )

        reward_score = np.asarray(data["rewards"], dtype=np.float32)
        raw_reward = reward_score
        reward_score = (reward_score + self.reward_bias) * self.reward_scaling
        clipped = np.clip(reward_score, -self.reward_clip, self.reward_clip)
        if self.rl_health is not None:
            self.rl_health.note_rewards(
                raw=raw_reward,
                clipped=clipped,
                clipped_frac=float((clipped != reward_score).mean()),
            )
        reward_score = clipped
        if self.reward_norm is not None:
            reward_score = self.reward_norm(reward_score)

        loss_mask = np.asarray(data["loss_mask"], dtype=np.float32)
        loss_mask = np.roll(loss_mask, shift=-1, axis=-1)

        if not cfg.use_decoupled_loss and cfg.recompute_logprob:
            # overwrite the inference engine's logprobs with the recomputed
            # ones (already next-token aligned from compute_logp)
            old_logp = data["logprobs"] = np.asarray(data["prox_logp"])
        else:
            old_logp = np.roll(np.asarray(data["logprobs"]), shift=-1, axis=-1)
            if not cfg.use_decoupled_loss:
                data["prox_logp"] = old_logp
        ref_logp = np.asarray(
            data.get("ref_logp", np.zeros_like(old_logp)), dtype=np.float32
        )
        ref_logp = ref_logp * loss_mask
        old_logp = old_logp * loss_mask

        attn_mask = np.asarray(data["attention_mask"])
        seqlens = attn_mask.sum(-1).astype(np.int64)
        seq_no_eos_mask = seqlens == attn_mask.shape[1]
        rewards = -self.kl_ctl * self.kl_estimator(old_logp, ref_logp)
        kl_rewards = rewards.copy()
        # no KL reward at/after the final token; task reward lands on the
        # second-to-last position (the one predicting EOS)
        rewards[batch_idx, seqlens - 1] = 0
        indices = np.clip(seqlens - 2, 0, None)
        if self.mask_no_eos_with_zero:
            rewards[batch_idx, indices] += np.where(seq_no_eos_mask, 0, reward_score)
        else:
            rewards[batch_idx, indices] += reward_score

        values = np.asarray(
            data.get("values", np.zeros_like(rewards)), dtype=np.float32
        )
        advantages = np.asarray(
            gae_padded(
                jnp.asarray(rewards, jnp.float32),
                jnp.asarray(values, jnp.float32),
                jnp.asarray(loss_mask, jnp.float32),
                jnp.asarray(seq_no_eos_mask),
                self.discount,
                self.gae_lambda,
            )
        )
        data["returns"] = advantages + values
        if self.adv_norm is not None:
            advantages = self.adv_norm(advantages, loss_mask)

        data["advantages"] = advantages.astype(np.float32)
        data["kl_rewards"] = kl_rewards.astype(np.float32)
        data["tot_rewards"] = rewards.astype(np.float32)
        data["loss_mask"] = loss_mask
        data["logprobs"] = old_logp

    def ppo_update(self, data: TensorDict) -> list[dict[str, float]]:
        """Minibatched policy update (reference actor.py:166-275)."""
        cfg = self.config
        if self.dynamic_sampling and len(data["rewards"]) % self.group_size == 0:
            data, _sampling_stat = dynamic_sampling(data, self.group_size)

        attn_mask = np.asarray(data["attention_mask"])
        loss_mask = np.asarray(data["loss_mask"])
        reward_score = np.asarray(data["rewards"], dtype=np.float32)
        seqlens = attn_mask.sum(-1)

        tracker = stats_tracker.DEFAULT_TRACKER
        tracker.denominator(
            n_seqs=np.ones_like(reward_score, dtype=bool),
            n_tokens=np.ones_like(loss_mask, dtype=bool),
            n_valid_tokens=loss_mask.astype(bool),
            correct_n_seqs=reward_score > 0,
            incorrect_n_seqs=reward_score <= 0,
        )
        tracker.stat(
            correct_seq_len=seqlens.astype(np.float32), denominator="correct_n_seqs"
        )
        tracker.stat(
            incorrect_seq_len=seqlens.astype(np.float32),
            denominator="incorrect_n_seqs",
        )
        tracker.stat(
            advantages=np.asarray(data["advantages"]),
            kl_rewards=np.asarray(data["kl_rewards"]),
            final_reward=np.asarray(data["tot_rewards"]),
            denominator="n_valid_tokens",
        )
        prompt_lens = attn_mask.sum(-1) - loss_mask.sum(-1)
        tracker.stat(
            no_eos_ratios=(seqlens == attn_mask.shape[-1]).astype(np.float32),
            task_reward=reward_score,
            prompt_len=prompt_lens.astype(np.float32),
            seq_len=seqlens.astype(np.float32),
            denominator="n_seqs",
        )
        global_stats = tracker.export()

        if self.rl_health is not None:
            # the observatory reads versions/logprobs/prox_logp/advantages
            # in the post-compute_advantages alignment — before the keys
            # below are dropped for the engine
            self.rl_health.observe_train_batch(
                data,
                current_version=int(self.engine.get_version() or 0),
                actor_config=cfg,
            )

        data = dict(data)
        for key in ["rewards", "tot_rewards", "kl_rewards", "versions"]:
            data.pop(key, None)

        # Loss aggregation mode (Dr.GRPO / LitePPO knob, cli_args.log_agg_mode).
        # token-mean leaves the engine's global sum/n_valid_tokens normalizer;
        # seq-mean modes attach per-token weights and normalize by n_seqs.
        mode = cfg.log_agg_mode
        if mode == "token-mean":
            loss_weight_fn = lambda x: np.asarray(x["loss_mask"]).sum()  # noqa: E731
        elif mode in ("seq-mean-token-sum", "seq-mean-token-mean"):
            lm = np.asarray(data["loss_mask"], dtype=np.float32)
            lens = np.maximum(lm.sum(-1, keepdims=True), 1.0)
            data["loss_agg_w"] = (
                np.ones_like(lm)
                if mode == "seq-mean-token-sum"
                else np.broadcast_to(1.0 / lens, lm.shape).astype(np.float32).copy()
            )
            loss_weight_fn = _count_seqs_with_loss
        else:
            raise ValueError(f"unknown log_agg_mode: {mode!r}")

        self.engine.train()
        mb_inputs = split_padded_tensor_dict_into_mb_list(
            data,
            max_tokens_per_mb=1 << 30,
            min_n_mbs=cfg.ppo_n_minibatches,
        )
        all_stats = []
        for mb in mb_inputs.mbs:
            train_stat = self.engine.train_batch(
                mb,
                loss_fn=self._loss_fn,
                loss_weight_fn=loss_weight_fn,
                token_loss_fn=self._token_loss_fn,
            )
            if self.rl_health is not None:
                self.rl_health.note_train_result(
                    loss=train_stat.get("loss"),
                    grad_norm=train_stat.get("grad_norm"),
                    update_successful=train_stat.get("update_successful"),
                )
            tracker.scalar(**train_stat)
            all_stats.append(tracker.export())
        all_stats[0].update(global_stats)
        return all_stats


# TPU engine-fused variant, mirroring the reference's FSDPPPOActor
# (actor.py:278): the engine IS the actor.
class TPUPPOActor(TPUTrainEngine):
    # recipes override this to swap algorithm behavior while keeping the
    # engine wiring (the reference's recipe/AEnt extension pattern)
    actor_cls = PPOActor

    def __init__(self, config: PPOActorConfig, **actor_kwargs):
        super().__init__(config)
        self.actor = self.actor_cls(config, self, **actor_kwargs)

    def compute_logp(self, *args, **kwargs):
        return self.actor.compute_logp(*args, **kwargs)

    def compute_advantages(self, *args, **kwargs):
        return self.actor.compute_advantages(*args, **kwargs)

    def ppo_update(self, *args, **kwargs):
        return self.actor.ppo_update(*args, **kwargs)

    # RPC-friendly variant (controller mode, scheduler/rpc.py whitelists
    # this — a raw ndarray return doesn't survive the wire)
    def compute_logp_named(self, data) -> dict:
        return {"logp": np.asarray(self.compute_logp(data))}


def _count_seqs_with_loss(x) -> float:
    """Number of sequences with >=1 valid loss token, for packed ([T] +
    cu_seqlens) or padded [B, S] microbatches."""
    lm = np.asarray(x["loss_mask"], dtype=np.float32)
    if lm.ndim == 1 and "cu_seqlens" in x:
        cu = np.asarray(x["cu_seqlens"])
        per_seq = np.add.reduceat(lm, cu[:-1]) if len(cu) > 1 else np.zeros(0)
    else:
        per_seq = lm.sum(-1)
    return float(np.count_nonzero(per_seq > 0))


def _calc_logprobs(logits, input_data, temperature: float = 1.0):
    labels = jnp.roll(input_data["input_ids"], shift=-1)
    return gather_logprobs(logits, labels, temperature)


def grpo_loss_fn(
    logits: jnp.ndarray,
    input_data: dict[str, Any],
    temperature: float,
    eps_clip: float,
    eps_clip_higher: float | None,
    c_clip: float | None,
    behav_imp_weight_cap: float | None,
    entropy_coeff: float = 0.0,
    entropy_clamp: float | None = None,
):
    """Packed decoupled-PPO loss, SUM-reduced over valid tokens (the engine
    divides by the global token count). Reference: actor.py:313-391; the
    entropy bonus is the AEnt recipe extension (recipe/AEnt/functional.py)."""
    labels = jnp.roll(input_data["input_ids"], shift=-1)
    logprobs, entropy = gather_logprobs_entropy(logits, labels, temperature)
    return grpo_loss_from_logp(
        logprobs,
        entropy,
        input_data,
        eps_clip=eps_clip,
        eps_clip_higher=eps_clip_higher,
        c_clip=c_clip,
        behav_imp_weight_cap=behav_imp_weight_cap,
        entropy_coeff=entropy_coeff,
        entropy_clamp=entropy_clamp,
    )


def grpo_loss_from_logp(
    logprobs: jnp.ndarray,
    entropy: jnp.ndarray,
    input_data: dict[str, Any],
    eps_clip: float,
    eps_clip_higher: float | None,
    c_clip: float | None,
    behav_imp_weight_cap: float | None,
    entropy_coeff: float = 0.0,
    entropy_clamp: float | None = None,
):
    """The loss math downstream of (logp, entropy) — shared by the classic
    logits path and the chunked fused-LM-head path (TokenLossFn)."""
    old_logp = input_data["logprobs"]
    advantages = input_data["advantages"]
    loss_mask = input_data["loss_mask"]
    prox_logp = input_data["prox_logp"]

    loss, _stat = ppo_actor_loss_fn(
        logprobs=logprobs,
        proximal_logprobs=prox_logp,
        old_logprobs=old_logp,
        advantages=advantages,
        eps_clip=eps_clip,
        loss_mask=loss_mask,
        eps_clip_higher=eps_clip_higher,
        c_clip=c_clip,
        behav_imp_weight_cap=behav_imp_weight_cap,
    )
    mask = loss_mask.astype(bool)
    count = jnp.maximum(jnp.sum(mask), 1)
    if "loss_agg_w" in input_data:
        # seq-mean aggregation modes (Dr.GRPO / LitePPO knob,
        # cli_args.log_agg_mode): per-token weights turn the engine's
        # global sum/normalize into mean-over-sequences of token-sum
        # (w=1, normalizer=n_seqs) or of token-mean (w=1/len(seq))
        scale = jnp.sum(jnp.where(mask, input_data["loss_agg_w"], 0.0))
        loss = jnp.sum(
            jnp.where(mask, _stat["loss"] * input_data["loss_agg_w"], 0.0)
        )
    else:
        scale = count
        loss = loss * count
    if entropy_coeff != 0.0:
        ent = entropy
        if entropy_clamp is not None:
            ent = jnp.minimum(ent, entropy_clamp)
        ent_bonus = jnp.sum(jnp.where(mask, ent, 0.0)) / count
        loss = loss - entropy_coeff * ent_bonus * scale
    return loss
