from areal_tpu.engine.sft.lm_engine import LMEngine, TPULMEngine

__all__ = ["LMEngine", "TPULMEngine"]
