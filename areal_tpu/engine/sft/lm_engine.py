"""SFT language-model engine: packed next-token cross-entropy.

Behavior parity with the reference's ``areal/engine/sft/lm_engine.py``
(FSDPLMEngine.train_lm/evaluate_lm): loss is the mean NLL over loss-masked
tokens, globally normalized across microbatches by the engine.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import TrainEngineConfig
from areal_tpu.engine.train_engine import TokenLossFn, TPUTrainEngine
from areal_tpu.utils.data import TensorDict
from areal_tpu.utils.functional import gather_logprobs


def sft_loss_fn(logits: jnp.ndarray, input_data) -> jnp.ndarray:
    """SUM-reduced masked NLL (engine divides by global mask count).

    ``loss_mask[t] == 1`` marks token t as a TARGET; both labels and mask
    roll by -1 to the next-token convention, so position t scores token t+1
    and sequence-boundary positions in the packed stream drop out (their
    rolled mask is the next sequence's first-token mask, always 0)."""
    labels = jnp.roll(input_data["input_ids"], shift=-1)
    logp = gather_logprobs(logits, labels)
    mask = jnp.roll(input_data["loss_mask"], shift=-1).astype(bool)
    return -jnp.sum(jnp.where(mask, logp, 0.0))


def _sft_token_loss_fn(logp, entropy, input_data) -> jnp.ndarray:
    """sft_loss_fn downstream of the chunked fused LM head."""
    mask = jnp.roll(input_data["loss_mask"], shift=-1).astype(bool)
    return -jnp.sum(jnp.where(mask, logp, 0.0))


SFT_TOKEN_LOSS = TokenLossFn(fn=_sft_token_loss_fn)


def _loss_weight(mb) -> float:
    return float(np.asarray(mb["loss_mask"]).sum())


class LMEngine:
    """Algorithm wrapper (reference lm_engine.py pattern)."""

    def __init__(self, engine: TPUTrainEngine):
        self.engine = engine

    def train_lm(self, data: TensorDict) -> dict[str, float]:
        self.engine.train()
        return self.engine.train_batch(
            input_=data, loss_fn=sft_loss_fn, loss_weight_fn=_loss_weight,
            token_loss_fn=SFT_TOKEN_LOSS,
        )

    def evaluate_lm(self, data: TensorDict) -> float | None:
        self.engine.train(False)
        return self.engine.eval_batch(
            input_=data, loss_fn=sft_loss_fn, loss_weight_fn=_loss_weight,
            token_loss_fn=SFT_TOKEN_LOSS,
        )


class TPULMEngine(TPUTrainEngine):
    """Engine-fused variant (reference FSDPLMEngine pattern)."""

    def __init__(self, config: TrainEngineConfig):
        super().__init__(config)
        self.lm = LMEngine(self)

    def train_lm(self, data: TensorDict) -> dict[str, float]:
        return self.lm.train_lm(data)

    def evaluate_lm(self, data: TensorDict) -> float | None:
        return self.lm.evaluate_lm(data)
