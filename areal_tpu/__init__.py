"""areal-tpu: a TPU-native asynchronous RL training framework.

A from-scratch JAX/XLA/pjit/Pallas re-design of the capabilities of AReaL
(the reference's layer map is documented in SURVEY.md): staleness-controlled
asynchronous rollout, decoupled-PPO/GRPO training over packed variable-length
sequences, GSPMD mesh parallelism (DP/FSDP/TP/SP/CP/EP), a continuous-batching
JAX inference engine with interruptible generation and in-place weight updates,
and launcher/recovery/observability infrastructure.
"""

__version__ = "0.1.0"

# Resolve version-forked jax symbols and align old-jax global semantics
# (e.g. partitionable threefry) BEFORE any submodule traces a computation.
from areal_tpu.utils import jax_compat as _jax_compat  # noqa: E402,F401
