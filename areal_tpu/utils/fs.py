"""Crash-consistent filesystem primitives for checkpoint/recover state.

Every file under a recover or checkpoint directory must be written via
write-then-rename: a preemption can land between any two syscalls, and a
reader (the next recovery run) must only ever see either the previous
complete file or the new complete file — never a truncated one. The
``crash-unsafe-write`` arealint rule flags direct write-mode ``open`` calls
on recovery-ish paths that bypass these helpers.

Fault injection: when ``AREAL_CHAOS_FS`` is armed (utils/chaos.fs_fault),
writes whose destination matches a spec fail deterministically — ENOSPC
before any bytes land, EIO at fsync, or a torn half-write — always BEFORE
the commit rename, exactly like the real failures they rehearse. The
durability tests pin that a dump hit mid-write leaves the previously
committed state fully intact and resumable. Off (the common case) costs
one env lookup per write.
"""

from __future__ import annotations

import errno
import json
import os


def atomic_write(path: str, write_fn, binary: bool = False) -> None:
    """Write via tmp-file + fsync + rename so readers never see a partial
    file. ``write_fn(f)`` receives the open tmp handle."""
    fault = None
    if os.environ.get("AREAL_CHAOS_FS"):
        from areal_tpu.utils.chaos import fs_fault

        fault = fs_fault(path)
    tmp = path + ".tmp"
    with open(tmp, "wb" if binary else "w") as f:
        if fault == "enospc":
            raise OSError(errno.ENOSPC, "chaos: injected ENOSPC", tmp)
        write_fn(f)
        f.flush()
        if fault == "short":
            # a torn write followed by a crash: half the bytes on the tmp
            # file, no rename — the committed target is untouched
            f.truncate(max(f.tell() // 2, 0))
            raise OSError(errno.EIO, "chaos: injected short write", tmp)
        if fault == "eio":
            raise OSError(errno.EIO, "chaos: injected EIO at fsync", tmp)
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write(path, lambda f: f.write(text))


def atomic_write_json(path: str, obj) -> None:
    atomic_write(path, lambda f: json.dump(obj, f))
