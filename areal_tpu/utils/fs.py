"""Crash-consistent filesystem primitives for checkpoint/recover state.

Every file under a recover or checkpoint directory must be written via
write-then-rename: a preemption can land between any two syscalls, and a
reader (the next recovery run) must only ever see either the previous
complete file or the new complete file — never a truncated one. The
``crash-unsafe-write`` arealint rule flags direct write-mode ``open`` calls
on recovery-ish paths that bypass these helpers.
"""

from __future__ import annotations

import json
import os


def atomic_write(path: str, write_fn, binary: bool = False) -> None:
    """Write via tmp-file + fsync + rename so readers never see a partial
    file. ``write_fn(f)`` receives the open tmp handle."""
    tmp = path + ".tmp"
    with open(tmp, "wb" if binary else "w") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write(path, lambda f: f.write(text))


def atomic_write_json(path: str, obj) -> None:
    atomic_write(path, lambda f: json.dump(obj, f))
