"""Distributed rollout tracing: follow ONE rollout across the process
boundary.

The counterpart of the reference's monitor layer (realhf/base/monitor.py
kernel-time attribution) for the *serving* plane: the trainer's counters
say how much time a step spent, but nothing in the repo could answer
"where did THIS rollout's 4 seconds go — queue, prefill, decode, a
failover, or a weight commit that landed mid-generation?". This module
gives every rollout a trace id minted in :class:`WorkflowExecutor`,
propagated as an ``x-areal-trace`` HTTP header through
:class:`RemoteInfEngine` into ``inference/server.py`` and the engine, so
client and server spans connect into one timeline:

- ``rollout`` (client, per episode) > ``generate`` (client, per
  agenerate call) > ``server.generate`` (server, per HTTP dispatch —
  one per failover/abort-resume splice, each tagged with its server
  address), with events for admission-queue wait, radix prefix-cache hit
  length, chunked-prefill dispatches, decode segments, spec-decode
  accept runs, and weight commits landing mid-generation.

Design constraints, in priority order:

1. **Near-zero cost off.** ``Tracer.from_config`` returns ``None`` when
   tracing is disabled, and every hot-path call site guards with ``is
   not None`` (the same discipline as the PR 3 chaos hook, pinned by a
   code-inspection test): the request path allocates NOTHING — no span
   objects, no kwargs dicts, no header strings.
2. **Bounded memory.** Finished spans land in a ring (``max_spans``);
   per-span events are capped (``max_events_per_span``). A tracer can
   run forever without growing.
3. **Exportable.** ``export_jsonl`` appends finished spans as JSON
   lines; :func:`chrome_trace` converts span dicts to the Chrome /
   Perfetto ``trace_event`` format so one rollout's life renders on a
   timeline next to a jax.profiler capture, and
   :func:`spans_from_chrome_trace` round-trips it back.

Clocks are injectable (``clock`` = monotonic for durations, ``wall`` =
epoch seconds for cross-process alignment) so tests drive fake time.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque

#: HTTP header carrying ``<trace_id>:<span_id>`` across the process
#: boundary (client generate span -> server request span).
TRACE_HEADER = "x-areal-trace"

#: contextvar linking an executor's rollout span to the agenerate calls
#: the workflow makes (workflow code in between needs no tracing API).
_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "areal_current_span", default=None
)


def current_span() -> "Span | None":
    return _CURRENT.get()


def set_current_span(span: "Span | None"):
    """Returns a token for :func:`reset_current_span`."""
    return _CURRENT.set(span)


def reset_current_span(token) -> None:
    _CURRENT.reset(token)


def parse_trace_header(value: str | None) -> tuple[str, str] | None:
    """``"<trace_id>:<span_id>"`` -> tuple, or None when absent/garbled
    (a malformed header from an old client must not fail the request)."""
    if not value:
        return None
    parts = value.split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]


class Span:
    """One timed operation. Mutated by its owner; ``event`` may be called
    from another thread (the engine thread stamps events onto a span the
    server loop owns) — ``list.append`` is atomic under the GIL and the
    event cap check is advisory, so no lock is needed."""

    __slots__ = (
        "tracer",
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "t_start",
        "t_wall",
        "t_end",
        "attrs",
        "events",
        "_ended",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: str | None,
        attrs: dict | None,
    ):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = tracer.clock()
        self.t_wall = tracer.wall()
        self.t_end: float | None = None
        self.attrs = dict(attrs) if attrs else {}
        self.events: list[dict] = []
        self._ended = False

    # -- recording ------------------------------------------------------

    def event(self, name: str, **attrs) -> None:
        """Append a point-in-time event; silently dropped past the cap
        (a bounded trace beats an unbounded one; the drop is counted)."""
        if len(self.events) >= self.tracer.max_events_per_span:
            self.tracer.events_dropped += 1
            return
        self.events.append(
            {"t": self.tracer.clock(), "name": name, **attrs}
        )

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def header(self) -> str:
        """Value for the :data:`TRACE_HEADER` of child requests."""
        return f"{self.trace_id}:{self.span_id}"

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.t_end = self.tracer.clock()
        self.tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs.setdefault("error", repr(exc)[:200])
        self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start": self.t_start,
            "t_wall": self.t_wall,
            "t_end": self.t_end,
            "attrs": self.attrs,
            "events": list(self.events),
        }


class Tracer:
    """Span factory + bounded buffer of finished spans.

    One tracer per component (client plane, each server process); spans
    from different tracers sharing a ``trace_id`` merge at export time —
    there is no cross-process buffer to synchronize.
    """

    def __init__(
        self,
        service: str = "areal",
        max_spans: int = 4096,
        max_events_per_span: int = 256,
        clock=time.monotonic,
        wall=time.time,
        export_path: str | None = None,
    ):
        self.service = service
        self.max_events_per_span = max_events_per_span
        self.clock = clock
        self.wall = wall
        self.export_path = export_path or None
        self._lock = threading.Lock()
        self._finished: deque[dict] = deque(maxlen=max_spans)  # guarded_by: _lock
        # lazily-opened persistent append handle for export_path: the
        # per-span cost with export on is one buffered write+flush, not
        # makedirs+open+close syscalls on the caller's (event-loop) thread
        self._export_lock = threading.Lock()
        self._export_fh = None  # guarded_by: _export_lock
        self._counter = itertools.count(1)
        # one random process prefix so span ids never collide across
        # processes sharing a trace id
        self._prefix = os.urandom(4).hex()
        self.spans_started = 0
        self.spans_finished = 0
        self.events_dropped = 0

    @classmethod
    def from_config(cls, cfg) -> "Tracer | None":
        """None when tracing is off — call sites then pay only an ``is
        not None`` check (the chaos-hook discipline)."""
        if cfg is None or not getattr(cfg, "enabled", False):
            return None
        return cls(
            service=getattr(cfg, "service", "areal") or "areal",
            max_spans=getattr(cfg, "max_spans", 4096),
            max_events_per_span=getattr(cfg, "max_events_per_span", 256),
            export_path=getattr(cfg, "export_path", None) or None,
        )

    # -- span creation --------------------------------------------------

    def _new_id(self) -> str:
        return f"{self._prefix}{next(self._counter):x}"

    def new_trace_id(self) -> str:
        return os.urandom(8).hex()

    def span(
        self,
        name: str,
        parent: "Span | None" = None,
        trace_id: str | None = None,
        parent_id: str | None = None,
        **attrs,
    ) -> Span:
        """Start a span. Parentage: explicit ``parent`` span wins, else
        (``trace_id``, ``parent_id``) from a propagated header, else a
        fresh root trace."""
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        elif trace_id is None:
            trace_id = self.new_trace_id()
        self.spans_started += 1
        attrs.setdefault("service", self.service)
        return Span(self, name, trace_id, self._new_id(), parent_id, attrs)

    def span_from_header(self, header: str | None, name: str, **attrs) -> Span:
        parsed = parse_trace_header(header)
        if parsed is None:
            return self.span(name, **attrs)
        trace_id, parent_id = parsed
        return self.span(
            name, trace_id=trace_id, parent_id=parent_id, **attrs
        )

    # -- buffer / export ------------------------------------------------

    def _finish(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            self._finished.append(d)
            self.spans_finished += 1
        if self.export_path:
            self._export_span(d)

    def finished_spans(self) -> list[dict]:
        with self._lock:
            return list(self._finished)

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def _export_span(self, d: dict) -> None:
        """Stream one finished span to ``export_path`` through a handle
        opened once and kept open — span end must not pay makedirs+open
        per span on the caller's thread (the server ends spans on its
        event loop). Flushed per span so readers (tests, the live verify
        recipe) see a span as soon as its request finishes."""
        try:
            with self._export_lock:
                fh = self._export_fh
                if fh is None:
                    dirn = os.path.dirname(self.export_path)
                    if dirn:
                        os.makedirs(dirn, exist_ok=True)
                    fh = self._export_fh = open(self.export_path, "a")
                fh.write(json.dumps(d) + "\n")
                fh.flush()
        except (OSError, ValueError):  # never fail the traced operation
            pass

    def close(self) -> None:
        """Release the export handle (idempotent; spans ended after a
        close() reopen it lazily)."""
        with self._export_lock:
            fh, self._export_fh = self._export_fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    @staticmethod
    def _append_jsonl(path: str, spans: list[dict]) -> None:
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                for s in spans:
                    f.write(json.dumps(s) + "\n")
        except OSError:  # tracing must never fail the traced operation
            pass

    def export_jsonl(self, path: str | None = None) -> int:
        """Append every buffered finished span to ``path`` (or the
        configured ``export_path``); returns the count written."""
        path = path or self.export_path
        if not path:
            raise ValueError("no export path configured")
        spans = self.finished_spans()
        self._append_jsonl(path, spans)
        return len(spans)


def read_spans_jsonl(*paths: str) -> list[dict]:
    """Load span dicts from one or more jsonl exports (client + each
    server) for a merged :func:`chrome_trace` — the cross-plane Perfetto
    join recipe. Garbled lines are skipped (a torn tail from a killed
    process must not void the rest of the trace)."""
    spans: list[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(d, dict) and "span_id" in d:
                        spans.append(d)
        except OSError:
            continue
    return spans


# ---------------------------------------------------------------------------
# Chrome / Perfetto trace_event export
# ---------------------------------------------------------------------------


def chrome_trace(spans: list[dict], time_base: str = "wall") -> dict:
    """Convert finished span dicts (possibly merged from several tracers
    — client + every server) to the Chrome ``trace_event`` JSON format
    Perfetto renders. Each distinct (service, trace component) becomes a
    process row; spans become complete ("X") events carrying their ids
    in ``args`` so :func:`spans_from_chrome_trace` can reconstruct them;
    span events become instant ("i") events on the same row.

    ``time_base="wall"`` anchors timestamps at each span's wall-clock
    start (cross-process alignment — monotonic clocks don't compare
    across hosts); events inside a span keep their monotonic offsets.
    """
    services = []
    events = []
    for s in spans:
        svc = str(s.get("attrs", {}).get("service", "areal"))
        if svc not in services:
            services.append(svc)
        pid = services.index(svc) + 1
        t_end = s["t_end"] if s["t_end"] is not None else s["t_start"]
        dur_us = max(0.0, (t_end - s["t_start"]) * 1e6)
        base_us = (
            s["t_wall"] * 1e6 if time_base == "wall" else s["t_start"] * 1e6
        )
        events.append(
            {
                "ph": "X",
                "name": s["name"],
                "cat": svc,
                "pid": pid,
                "tid": 1,
                "ts": base_us,
                "dur": dur_us,
                "args": {
                    "trace_id": s["trace_id"],
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                    "t_wall": s["t_wall"],
                    **{
                        k: v
                        for k, v in s.get("attrs", {}).items()
                        if k != "service"
                    },
                },
            }
        )
        for ev in s.get("events", []):
            events.append(
                {
                    "ph": "i",
                    "name": ev["name"],
                    "cat": svc,
                    "pid": pid,
                    "tid": 1,
                    "s": "t",
                    "ts": base_us + (ev["t"] - s["t_start"]) * 1e6,
                    "args": {
                        "span_id": s["span_id"],
                        **{
                            k: v
                            for k, v in ev.items()
                            if k not in ("t", "name")
                        },
                    },
                }
            )
    for i, svc in enumerate(services):
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": i + 1,
                "tid": 0,
                "ts": 0,
                "args": {"name": svc},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spans_from_chrome_trace(trace: dict) -> list[dict]:
    """Inverse of :func:`chrome_trace` (lossless for ids, names, timing,
    attrs, and events) — pins that the Perfetto export round-trips."""
    spans: dict[str, dict] = {}
    pid_to_service = {}
    # the emitted base timestamp per span — event offsets are relative to
    # it whatever time_base produced the trace (wall OR monotonic start)
    base_us: dict[str, float] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_to_service[ev["pid"]] = ev["args"]["name"]
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        trace_id = args.pop("trace_id")
        span_id = args.pop("span_id")
        parent_id = args.pop("parent_id", None)
        t_wall = args.pop("t_wall", ev["ts"] / 1e6)
        t_start = t_wall
        base_us[span_id] = ev["ts"]
        spans[span_id] = {
            "name": ev["name"],
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "t_start": t_start,
            "t_wall": t_wall,
            "t_end": t_start + ev.get("dur", 0.0) / 1e6,
            "attrs": {
                "service": pid_to_service.get(ev["pid"], "areal"),
                **args,
            },
            "events": [],
        }
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "i":
            continue
        args = dict(ev.get("args", {}))
        sid = args.pop("span_id", None)
        s = spans.get(sid)
        if s is None:
            continue
        s["events"].append(
            {
                "t": s["t_start"] + (ev["ts"] - base_us[sid]) / 1e6,
                "name": ev["name"],
                **args,
            }
        )
    return list(spans.values())
