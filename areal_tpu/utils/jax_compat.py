"""Version-compat layer for JAX APIs that forked across the supported matrix.

THE one home for every version-forked jax symbol this repo touches — all
consumers (ops/, parallel/, inference/) import from here instead of probing
jax themselves, and the ``jax-compat`` arealint rule enforces it
(areal_tpu/lint/rules/jax_compat.py: direct ``shard_map`` / Pallas
compiler-params imports outside this module are findings).

Two jax generations are supported:

- **new** (>= 0.5-era): ``jax.shard_map`` with ``axis_names=`` /
  ``check_vma=`` and abstract-mesh nesting
  (``jax.sharding.get_abstract_mesh``); Pallas TPU params are
  ``pltpu.CompilerParams``.
- **old** (0.4.x, what this image ships): ``shard_map`` lives at
  ``jax.experimental.shard_map.shard_map`` with the complementary
  ``auto=`` / ``check_rep=`` spelling; Pallas TPU params are
  ``pltpu.TPUCompilerParams``.

The rename layer is the easy half. The hard half is that on 0.4.x the
**partial-auto** shard_map mode (manual over a subset of mesh axes, the
rest auto so GSPMD keeps sharding the stage interior — how every pipeline
schedule in parallel/pipeline.py runs) has broken collective lowering on
CPU: ``ppermute`` / ``all_gather`` / ``all_to_all`` abort inside the XLA
SPMD partitioner (``Check failed: target.IsManualSubgroup() ==
sharding().IsManualSubgroup()``) and ``axis_index`` lowers to an
unpartitionable ``PartitionId`` op — while ``psum`` / ``psum_scatter``
lower fine. So :func:`shard_map` here detects the degraded regime (old
jax AND any auto axis with extent > 1) and the collective wrappers below
(:func:`axis_index`, :func:`ppermute`, :func:`all_gather`,
:func:`all_to_all`) transparently fall back to psum-based equivalents:

- the wrapper feeds each manual axis's coordinate in as DATA (a sharded
  iota extra argument) and stashes it in a trace-local context, so
  :func:`axis_index` never emits ``PartitionId``;
- ``ppermute``/``all_gather``/``all_to_all`` one-hot-scatter their operand
  into a per-shard slot of a zeros table and ``psum`` it, then slice the
  receiver's entry — O(n) extra bandwidth, which only ever runs in CPU
  rehearsal (new jax on TPU takes the native path), and differentiable by
  construction (dynamic-update-slice + psum), so AD through pipeline
  schedules keeps working.

Everything here is trace-time dispatch: ``interpret``/jit/scan/vjp see
ordinary lax ops either way.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# --------------------------------------------------------------------------
# Version probes (computed once at import)
# --------------------------------------------------------------------------

#: True on jax generations that ship ``jax.shard_map`` natively.
HAS_NATIVE_SHARD_MAP: bool = hasattr(jax, "shard_map")

try:  # pragma: no cover - absent only on exotic builds
    from jax.experimental.pallas import tpu as _pltpu
except Exception:  # noqa: BLE001
    _pltpu = None

#: The Pallas TPU compiler-params class under its version-local name.
if _pltpu is not None and hasattr(_pltpu, "CompilerParams"):
    TPUCompilerParams = _pltpu.CompilerParams
elif _pltpu is not None:
    TPUCompilerParams = _pltpu.TPUCompilerParams
else:  # pragma: no cover
    TPUCompilerParams = None


def pallas_compiler_params(**kwargs) -> Any:
    """Construct Pallas TPU compiler params under either spelling.

    ``pallas_compiler_params(dimension_semantics=("parallel", "arbitrary"))``
    returns ``pltpu.CompilerParams(...)`` on new jax and
    ``pltpu.TPUCompilerParams(...)`` on 0.4.x.
    """
    if TPUCompilerParams is None:  # pragma: no cover
        raise RuntimeError("jax.experimental.pallas.tpu is unavailable")
    return TPUCompilerParams(**kwargs)


if HAS_NATIVE_SHARD_MAP:
    _native_shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _native_shard_map

    # New jax defaults to the partitionable threefry; 0.4.x defaults to the
    # legacy layout-DEPENDENT one, where `jit(init, out_shardings=...)` over
    # a tp-sharded leaf generates different values than the unsharded call —
    # exactly the single-device-vs-mesh init divergence the engine equality
    # tests pin. Align old jax to the new-jax semantics.
    jax.config.update("jax_threefry_partitionable", True)


# --------------------------------------------------------------------------
# Trace-local manual-axis context (the degraded-collective side channel)
# --------------------------------------------------------------------------

# Tracing is single-threaded per trace; a thread-local stack of
# {axis: (coord_tracer, size)} frames survives nested compat shard_maps.
_tls = threading.local()


def _ctx_stack() -> list[dict[str, tuple[Any, int]]]:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def _current_ctx() -> dict[str, tuple[Any, int]]:
    stack = _ctx_stack()
    return stack[-1] if stack else {}


@contextlib.contextmanager
def _pushed_ctx(frame: dict[str, tuple[Any, int]]):
    merged = dict(_current_ctx())
    merged.update(frame)
    _ctx_stack().append(merged)
    try:
        yield
    finally:
        _ctx_stack().pop()


def _in_degraded_region() -> bool:
    return bool(_current_ctx())


def _axes_tuple(axis_name) -> tuple:
    return (axis_name,) if not isinstance(axis_name, (tuple, list)) else tuple(
        axis_name
    )


def _combined_index_size(axis_name) -> tuple[Any, int]:
    """(linear index along the flattened axis group, group size), matching
    jax's left-major flattening of multi-axis collectives."""
    ctx = _current_ctx()
    idx = None
    total = 1
    for a in _axes_tuple(axis_name):
        if a not in ctx:
            raise KeyError(
                f"axis {a!r} is not a manual axis of the enclosing compat "
                f"shard_map (have {sorted(ctx)})"
            )
        coord, n = ctx[a]
        idx = coord if idx is None else idx * n + coord
        total *= n
    return idx, total


# --------------------------------------------------------------------------
# shard_map
# --------------------------------------------------------------------------


def shard_map(
    f: Callable,
    mesh: Any = None,
    in_specs: Any = None,
    out_specs: Any = None,
    *,
    check_vma: bool = True,
    axis_names: frozenset | set | None = None,
    nested_manual: frozenset = frozenset(),
    diff_argnums: tuple | None = None,
    _force_degraded: bool = False,
) -> Callable:
    """Version-portable ``shard_map``.

    Parameters follow the NEW jax spelling (``axis_names`` = the axes this
    map manualizes, ``check_vma``); the shim translates for 0.4.x
    (``auto`` = complement, ``check_rep``). ``nested_manual`` names axes an
    ENCLOSING shard_map already manualized: on new jax the inner map then
    runs on the context abstract mesh (``jax.sharding.get_abstract_mesh``);
    on old jax it keeps the concrete mesh and simply excludes those axes
    from ``auto``.

    Old-jax degraded regime: when any ``auto`` axis has extent > 1, the
    native collectives this repo uses inside manual regions crash XLA's
    SPMD partitioner on CPU, so the wrapper feeds each manual axis's
    coordinate in as a sharded-iota extra argument and arms the
    psum-fallback paths of :func:`axis_index` / :func:`ppermute` /
    :func:`all_gather` / :func:`all_to_all` for the duration of the trace.
    ``in_specs`` must be a tuple/list matching ``f``'s positional args (all
    repo call sites comply) so the extra iota specs can be appended.
    """
    if axis_names is not None:
        axis_names = frozenset(axis_names)

    if HAS_NATIVE_SHARD_MAP:
        use_mesh = mesh
        extra = {}
        if axis_names is not None:
            extra["axis_names"] = axis_names
        if nested_manual:
            use_mesh = jax.sharding.get_abstract_mesh()
        return _native_shard_map(
            f,
            mesh=use_mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
            **extra,
        )

    all_axes = tuple(mesh.axis_names)
    manual = (
        frozenset(all_axes) - frozenset(nested_manual)
        if axis_names is None
        else axis_names
    )
    auto = frozenset(all_axes) - manual - frozenset(nested_manual)
    degraded = any(int(mesh.shape[a]) > 1 for a in auto)
    region_degraded = _in_degraded_region()

    if not degraded and not region_degraded and not _force_degraded:
        return _native_shard_map(
            f,
            mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=False,
            auto=auto,
        )

    # Degraded: thread per-manual-axis coordinates in as data. (Also taken
    # when an ENCLOSING compat map is degraded, so nested maps keep the
    # coordinate frames flowing and their collectives stay on the psum
    # fallback too.)
    man_axes = tuple(a for a in all_axes if a in manual)
    if not isinstance(in_specs, (tuple, list)):
        raise TypeError(
            "compat shard_map needs tuple in_specs to append axis-coordinate "
            f"inputs in the old-jax degraded regime, got {type(in_specs)}"
        )
    ext_specs = tuple(in_specs) + tuple(P(a) for a in man_axes)

    def f_ext(*args):
        coords = args[len(args) - len(man_axes):]
        inner = args[: len(args) - len(man_axes)]
        frame = {
            a: (c[0], int(mesh.shape[a])) for a, c in zip(man_axes, coords)
        }
        with _pushed_ctx(frame):
            return f(*inner)

    mapped = _native_shard_map(
        f_ext,
        mesh,
        in_specs=ext_specs,
        out_specs=out_specs,
        check_rep=False,
        auto=auto,
    )

    def call(*args):
        iotas = tuple(
            jnp.arange(int(mesh.shape[a]), dtype=jnp.int32) for a in man_axes
        )
        return mapped(*args, *iotas)

    if region_degraded and diff_argnums is not None:
        # NESTED map on a differentiated path. jax 0.4.x cannot transpose a
        # shard_map nested inside another manual region: partial-eval names
        # the inner map's residual outputs over EVERY mesh axis, and
        # lowering that spec inside the enclosing manual context trips
        # "Axis ... is also found in manual_axes". Hide the nesting from
        # AD entirely: custom_vjp whose backward rebuilds a FRESH
        # forward-only nested map that recomputes f and pulls the
        # cotangent through jax.vjp INSIDE the map body (so only safe,
        # already-degraded collectives appear in the transposed program).
        if isinstance(out_specs, (tuple, list)) and not isinstance(
            out_specs, P
        ):
            raise NotImplementedError(
                "diff_argnums recompute-vjp supports single-output maps"
            )
        argnums = tuple(diff_argnums)

        @jax.custom_vjp
        def cv(*args):
            return call(*args)

        def cv_fwd(*args):
            return call(*args), args

        def cv_bwd(res, ct):
            args = res

            def bwd_body(*a):
                prim, ct_l = a[:-1], a[-1]

                def g(*diff):
                    full = list(prim)
                    for i, d in zip(argnums, diff):
                        full[i] = d
                    return f(*full)

                _, pull = jax.vjp(g, *[prim[i] for i in argnums])
                return pull(ct_l)

            bwd_map = shard_map(
                bwd_body,
                mesh,
                in_specs=tuple(in_specs) + (out_specs,),
                out_specs=tuple(in_specs[i] for i in argnums),
                check_vma=check_vma,
                axis_names=axis_names,
                nested_manual=nested_manual,
                _force_degraded=True,
            )
            gs = bwd_map(*args, ct)
            out = [None] * len(args)
            for i, g_ in zip(argnums, gs):
                out[i] = g_
            return tuple(out)

        cv.defvjp(cv_fwd, cv_bwd)
        return cv

    return call


# --------------------------------------------------------------------------
# Collectives (native when safe, psum-based in the degraded regime)
# --------------------------------------------------------------------------


def axis_index(axis_name) -> jnp.ndarray:
    """``jax.lax.axis_index`` that stays legal in the degraded regime by
    reading the data-borne coordinate instead of emitting PartitionId."""
    if _in_degraded_region():
        idx, _ = _combined_index_size(axis_name)
        return idx
    return jax.lax.axis_index(axis_name)


def ppermute(x: jnp.ndarray, axis_name, perm: Sequence[tuple]) -> jnp.ndarray:
    """``jax.lax.ppermute`` with a psum fallback in the degraded regime.

    Fallback: every shard one-hot-scatters its operand into row ``dst`` of
    an ``[n, ...]`` zeros table (rows of senders with no target stay
    zero, matching ppermute's zeros-for-unsourced semantics), psums the
    table over the axis group, and slices its own row.
    """
    if not _in_degraded_region():
        return jax.lax.ppermute(x, axis_name, perm)
    idx, n = _combined_index_size(axis_name)
    import numpy as np

    dst_of = np.full((n,), -1, np.int32)
    for src, dst in perm:
        dst_of[src] = dst
    dst = jnp.asarray(dst_of)[idx]
    table = jnp.zeros((n,) + x.shape, x.dtype)
    # senders without a target park their row in a scratch slot n
    table = jnp.concatenate([table, jnp.zeros((1,) + x.shape, x.dtype)])
    table = jax.lax.dynamic_update_slice(
        table,
        x[None].astype(x.dtype),
        (jnp.where(dst >= 0, dst, n),) + (0,) * x.ndim,
    )
    full = jax.lax.psum(table[:n], axis_name)
    return jax.lax.dynamic_index_in_dim(full, idx, 0, keepdims=False)


def all_gather(
    x: jnp.ndarray, axis_name, *, axis: int = 0, tiled: bool = False
) -> jnp.ndarray:
    """``jax.lax.all_gather`` with a psum fallback in the degraded regime."""
    if not _in_degraded_region():
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    idx, n = _combined_index_size(axis_name)
    table = jnp.zeros((n,) + x.shape, x.dtype)
    table = jax.lax.dynamic_update_slice(
        table, x[None], (idx,) + (0,) * x.ndim
    )
    full = jax.lax.psum(table, axis_name)  # [n, ...]
    if tiled:
        # concatenate along ``axis``
        parts = [
            jax.lax.index_in_dim(full, i, 0, keepdims=False) for i in range(n)
        ]
        return jnp.concatenate(parts, axis=axis)
    return jnp.moveaxis(full, 0, axis)


def all_to_all(
    x: jnp.ndarray,
    axis_name,
    split_axis: int,
    concat_axis: int,
    *,
    tiled: bool = False,
) -> jnp.ndarray:
    """``jax.lax.all_to_all`` with a psum fallback in the degraded regime."""
    if not _in_degraded_region():
        return jax.lax.all_to_all(
            x, axis_name, split_axis, concat_axis, tiled=tiled
        )
    if not tiled:
        raise NotImplementedError(
            "degraded all_to_all supports tiled=True only (the repo's "
            "ulysses path)"
        )
    idx, n = _combined_index_size(axis_name)
    assert x.shape[split_axis] % n == 0, (x.shape, split_axis, n)
    # pieces[j] = the chunk this shard sends to receiver j
    pieces = jnp.stack(jnp.split(x, n, axis=split_axis))  # [n, ...chunk]
    # table[recv, sender] = chunk; each sender fills column ``idx``
    table = jnp.zeros((n, n) + pieces.shape[1:], x.dtype)
    table = jax.lax.dynamic_update_slice(
        table,
        pieces[:, None],
        (0, idx) + (0,) * (pieces.ndim - 1),
    )
    full = jax.lax.psum(table, axis_name)  # [recv, sender, ...chunk]
    mine = jax.lax.dynamic_index_in_dim(full, idx, 0, keepdims=False)
    parts = [
        jax.lax.index_in_dim(mine, i, 0, keepdims=False) for i in range(n)
    ]
    return jnp.concatenate(parts, axis=concat_axis)


def scan(
    body: Callable,
    init,
    xs=None,
    length: int | None = None,
    *,
    unroll: bool = False,
):
    """``jax.lax.scan`` that stays compilable in the degraded regime.

    On 0.4.x CPU, a scan that STACKS per-step outputs (``ys``) inside a
    partial-auto manual region trips ``hlo_sharding_util.cc: Check failed:
    sharding.IsManualSubgroup()`` in the SPMD partitioner (carry-only scans
    are fine). Worse, DIFFERENTIATING any scan there re-introduces ys
    stacking internally (partial-eval saves per-iteration residuals as
    stacked outputs), so grad-carrying scans crash even when carry-only.

    Fallbacks, degraded regime only:

    - ``unroll=False`` (most forward/serving paths): rewrite the scan to
      accumulate each ``ys`` leaf into a preallocated carry buffer via
      ``dynamic_update_index_in_dim`` — same memory, same values, still one
      XLA while loop. Carry-only scans pass through natively.
    - ``unroll=True``: unroll the loop in Python — no scan primitive exists
      in the traced program at all. REQUIRED for anything under
      jax.grad/jax.vjp (AD's residual stacking re-crashes even carry-only
      scans) and for bodies whose carry scatters trip the partitioner even
      without ys (the rotated serving conveyors). Compile time grows with
      the step count, which is bounded in the CPU-rehearsal tier where
      this regime runs (new jax on TPU takes the native path).
    """
    if not _in_degraded_region():
        return jax.lax.scan(body, init, xs, length)

    if xs is None:
        n = int(length)
    else:
        n = jax.tree_util.tree_leaves(xs)[0].shape[0]

    if unroll:
        c = init
        ys = []
        for i in range(n):
            x = (
                jax.tree.map(lambda a: a[i], xs) if xs is not None else None
            )
            c, y = body(c, x)
            ys.append(y)
        if not ys or not jax.tree_util.tree_leaves(ys[0]):
            return c, None
        stacked = jax.tree.map(lambda *vs: jnp.stack(vs), *ys)
        return c, stacked

    xs_elt = jax.tree.map(lambda a: a[0], xs) if xs is not None else None
    _, ys_shape = jax.eval_shape(lambda c, x: body(c, x), init, xs_elt)
    if not jax.tree_util.tree_leaves(ys_shape):
        return jax.lax.scan(body, init, xs, length)

    bufs0 = jax.tree.map(
        lambda sd: jnp.zeros((n,) + sd.shape, sd.dtype), ys_shape
    )

    def body2(carry2, x):
        i, c, bufs = carry2
        c2, y = body(c, x)
        bufs2 = jax.tree.map(
            lambda b, yv: jax.lax.dynamic_update_index_in_dim(b, yv, i, 0),
            bufs,
            y,
        )
        return (i + 1, c2, bufs2), None

    (_, c_fin, ys), _ = jax.lax.scan(
        body2, (jnp.int32(0), init, bufs0), xs, length
    )
    return c_fin, ys


def top_k(x: jnp.ndarray, k: int):
    """``jax.lax.top_k`` that stays compilable in the degraded regime.

    The native op's partitioner hits the same manual-subgroup CHECK as the
    collectives on 0.4.x CPU; the fallback is a stable descending argsort
    (identical values AND tie-breaking: lowest index first)."""
    if not _in_degraded_region():
        return jax.lax.top_k(x, k)
    idx = jnp.argsort(-x, axis=-1, stable=True)[..., :k]
    return jnp.take_along_axis(x, idx, axis=-1), idx.astype(jnp.int32)


def set_mesh(mesh):
    """``jax.set_mesh(mesh)`` context manager under either jax generation.

    New jax exposes ``jax.set_mesh`` (sharding-in-types ambient mesh); on
    0.4.x entering the ``Mesh`` itself provides the ambient-mesh context.
    """
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def psum(x, axis_name):
    """``jax.lax.psum`` (safe in every regime; re-exported so manual-region
    code can import ALL its collectives from one place)."""
    return jax.lax.psum(x, axis_name)


def psum_scatter(x, axis_name, *, scatter_dimension: int = 0, tiled: bool = False):
    """``jax.lax.psum_scatter`` with a psum+slice fallback in the degraded
    regime. The native op lowers fine there, but its TRANSPOSE is
    ``all_gather`` — so any psum_scatter on a differentiated path aborts in
    backward. ``psum`` transposes to ``psum``, keeping AD inside the safe
    collective set."""
    if not _in_degraded_region():
        return jax.lax.psum_scatter(
            x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
        )
    if not tiled:
        raise NotImplementedError(
            "degraded psum_scatter supports tiled=True only"
        )
    idx, n = _combined_index_size(axis_name)
    full = jax.lax.psum(x, axis_name)
    blk = x.shape[scatter_dimension] // n
    start = idx * blk
    starts = [0] * x.ndim
    starts[scatter_dimension] = start
    sizes = list(x.shape)
    sizes[scatter_dimension] = blk
    return jax.lax.dynamic_slice(full, tuple(starts), tuple(sizes))
