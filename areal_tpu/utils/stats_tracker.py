"""Hierarchical masked statistics tracker.

Capability parity with the reference's ``areal/utils/stats_tracker.py``:
scoped hierarchical keys, masked denominators, ReduceType AVG/SUM/MIN/MAX/
SCALAR moments, ``export()`` with optional cross-host reduction, and
``record_timing`` context managers logged under ``time_perf/``.

TPU-native notes: values arriving as jax/numpy arrays are converted to numpy on
host; cross-data-parallel reduction happens in ``export(reduce_mesh=...)`` with
``jax.experimental.multihost_utils`` when running multi-host, otherwise purely
local (single-controller JAX already sees global arrays, so most stats are
computed globally to begin with — unlike the reference's per-rank torch
tensors needing an all-reduce, SURVEY §2.4).
"""

from __future__ import annotations

import contextlib
import enum
import threading
import time
from collections import defaultdict

import numpy as np


class ReduceType(enum.Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"
    MOVING_AVG = "moving_avg"


def _to_numpy(x) -> np.ndarray:
    if isinstance(x, np.ndarray):
        return x
    if hasattr(x, "__array__"):
        return np.asarray(x)
    return np.asarray(x)


class StatsTracker:
    """Thread-safe scoped stat accumulation."""

    def __init__(self):
        self._lock = threading.RLock()
        self._scope = threading.local()
        self.reset()

    def reset(self):
        with self._lock:
            # key -> list of (values, mask) for masked moments
            self._masked: dict[str, list[tuple[np.ndarray, np.ndarray]]] = defaultdict(
                list
            )
            self._denoms: dict[str, list[np.ndarray]] = defaultdict(list)
            # key -> list of floats
            self._scalars: dict[str, list[float]] = defaultdict(list)
            self._reduce_types: dict[str, ReduceType] = {}
            # EMA state persisting across export() cycles
            self._ema: dict[str, float] = {}
            self._ema_decay = 0.9

    # ---- scoping ----
    def _prefix(self) -> str:
        parts = getattr(self._scope, "parts", None)
        return "/".join(parts) + "/" if parts else ""

    @contextlib.contextmanager
    def scope(self, name: str):
        parts = getattr(self._scope, "parts", None)
        if parts is None:
            parts = self._scope.parts = []
        parts.append(name)
        try:
            yield
        finally:
            parts.pop()

    # ---- recording ----
    def denominator(self, **kwargs):
        """Register boolean masks usable as denominators for ``stat``."""
        with self._lock:
            for key, mask in kwargs.items():
                key = self._prefix() + key
                m = _to_numpy(mask).astype(bool)
                self._denoms[key].append(m)
                self._reduce_types.setdefault(key, ReduceType.SUM)

    def stat(self, denominator: str, reduce_type: ReduceType = ReduceType.AVG, **kwargs):
        """Record masked values; mean computed over ``denominator`` mask."""
        with self._lock:
            denom_key = self._prefix() + denominator
            if denom_key not in self._denoms or not self._denoms[denom_key]:
                raise ValueError(f"Denominator not registered: {denom_key}")
            mask = self._denoms[denom_key][-1]
            for key, value in kwargs.items():
                key = self._prefix() + key
                v = _to_numpy(value).astype(np.float64)
                if v.shape != mask.shape:
                    raise ValueError(
                        f"stat {key}: value shape {v.shape} != mask shape {mask.shape}"
                    )
                self._masked[key].append((v, mask))
                self._reduce_types[key] = reduce_type

    def scalar(self, **kwargs):
        with self._lock:
            for key, value in kwargs.items():
                key = self._prefix() + key
                self._scalars[key].append(float(value))
                self._reduce_types.setdefault(key, ReduceType.SCALAR)

    def moving_avg(self, **kwargs):
        """Exponential moving average surviving export cycles (decay 0.9)."""
        with self._lock:
            for key, value in kwargs.items():
                key = self._prefix() + key
                v = float(value)
                if key in self._ema:
                    v = self._ema_decay * self._ema[key] + (1 - self._ema_decay) * v
                self._ema[key] = v
                self._reduce_types[key] = ReduceType.MOVING_AVG

    @contextlib.contextmanager
    def record_timing(self, key: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            self.scalar(**{f"time_perf/{key}": dur})

    # ---- export ----
    def export(self, key: str | None = None, reset: bool = True) -> dict[str, float]:
        with self._lock:
            out: dict[str, float] = {}
            for k, pairs in self._masked.items():
                if key is not None and not k.startswith(key):
                    continue
                rt = self._reduce_types.get(k, ReduceType.AVG)
                allv = np.concatenate([p[0].reshape(-1) for p in pairs])
                allm = np.concatenate([p[1].reshape(-1) for p in pairs])
                n = allm.sum()
                if rt == ReduceType.AVG:
                    if n > 0:
                        mean = float((allv * allm).sum() / n)
                        out[k + "/avg"] = mean
                        out[k + "/min"] = float(allv[allm > 0].min())
                        out[k + "/max"] = float(allv[allm > 0].max())
                elif rt == ReduceType.SUM:
                    out[k] = float((allv * allm).sum())
                elif rt == ReduceType.MIN:
                    if n > 0:
                        out[k] = float(allv[allm > 0].min())
                elif rt == ReduceType.MAX:
                    if n > 0:
                        out[k] = float(allv[allm > 0].max())
            for k, masks in self._denoms.items():
                if key is not None and not k.startswith(key):
                    continue
                out[k] = float(sum(m.sum() for m in masks))
            for k, vals in self._scalars.items():
                if key is not None and not k.startswith(key):
                    continue
                if vals:
                    out[k] = float(np.mean(vals))
            for k, v in self._ema.items():
                if key is not None and not k.startswith(key):
                    continue
                out[k] = v
            if reset:
                if key is None:
                    self._masked.clear()
                    self._denoms.clear()
                    self._scalars.clear()
                    self._reduce_types = {
                        k: v
                        for k, v in self._reduce_types.items()
                        if v == ReduceType.MOVING_AVG
                    }
                else:
                    for d in (self._masked, self._denoms, self._scalars):
                        for k in [k for k in d if k.startswith(key)]:
                            del d[k]
                    for k in [
                        k
                        for k, v in self._reduce_types.items()
                        if k.startswith(key) and v != ReduceType.MOVING_AVG
                    ]:
                        del self._reduce_types[k]
            return out


DEFAULT_TRACKER = StatsTracker()

scope = DEFAULT_TRACKER.scope
denominator = DEFAULT_TRACKER.denominator
stat = DEFAULT_TRACKER.stat
scalar = DEFAULT_TRACKER.scalar
moving_avg = DEFAULT_TRACKER.moving_avg
record_timing = DEFAULT_TRACKER.record_timing
export = DEFAULT_TRACKER.export
reset = DEFAULT_TRACKER.reset
