"""Hung-trainer watchdog: crash loudly instead of wedging silently.

A preempted trainer dies and the launcher restarts it — but a WEDGED
trainer (deadlocked collective, rollout wait against a dead fleet, stuck
host callback) sits at 0% forever and no supervisor notices, which on a
paid TPU slice is strictly worse than crashing. The watchdog inverts that:
the training loop calls :meth:`Watchdog.beat` at every phase boundary
(rollout wait, train step, weight update, checkpoint), and a daemon thread
verifies the gap between beats never exceeds ``timeout_seconds``. On a
miss it dumps EVERY thread's stack (the post-mortem for "where was it
stuck") and exits with ``config.exit_code`` so the launcher's
relaunch-with-backoff loop restarts the trial from the last recover dump.

``clock``/``exit_fn`` are injectable so tests drive a fake clock and
capture the exit instead of dying.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from areal_tpu.api.cli_args import WatchdogConfig
from areal_tpu.utils import logging

logger = logging.getLogger("watchdog")


def dump_all_stacks(file=None) -> str:
    """Format every live thread's stack (the hang post-mortem). Returns the
    text; also writes it to ``file`` when given."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in frames.items():
        parts.append(
            f"--- thread {names.get(ident, '?')} (ident {ident}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    text = "\n".join(parts)
    if file is not None:
        file.write(text)
        file.flush()
    return text


class Watchdog:
    """Heartbeat monitor around the training loop's phase boundaries."""

    def __init__(
        self,
        config: WatchdogConfig,
        clock=time.monotonic,
        exit_fn=None,
    ):
        self.config = config
        self._clock = clock
        # os._exit, not sys.exit: the whole point is that ordinary control
        # flow is stuck — atexit handlers or a blocked main thread must not
        # be able to swallow the exit
        self._exit_fn = exit_fn if exit_fn is not None else os._exit
        self._lock = threading.Lock()
        self._last_beat: float = clock()  # guarded_by: _lock
        self._last_phase: str = "startup"  # guarded_by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fired = False

    def start(self) -> "Watchdog":
        if not self.config.enabled:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def beat(self, phase: str) -> None:
        """Mark liveness at a phase boundary. Cheap (one lock, no I/O) —
        call it freely; the phase name appears in the hang report."""
        with self._lock:
            self._last_beat = self._clock()
            self._last_phase = phase

    def check(self) -> bool:
        """One poll: fire if the heartbeat gap exceeded the timeout.
        Exposed for tests and for loops that poll explicitly."""
        with self._lock:
            gap = self._clock() - self._last_beat
            phase = self._last_phase
        if gap <= self.config.timeout_seconds:
            return False
        self.fired = True
        # evidence first: the flight recorder's ring buffers (recent
        # requests, breaker transitions, weight commits) are the context
        # the stack dump below lacks; best-effort — a recorder failure
        # must not block the exit that is the watchdog's whole job
        try:
            from areal_tpu.utils import flight_recorder

            flight_recorder.dump("watchdog")
        except Exception:
            logger.debug("watchdog flight dump failed", exc_info=True)
        report = dump_all_stacks()
        logger.error(
            "watchdog: no heartbeat for %.0fs (last phase %r, timeout "
            "%.0fs); trainer is wedged — dumping stacks and exiting %d "
            "so the launcher restarts from the last recover dump\n%s",
            gap,
            phase,
            self.config.timeout_seconds,
            self.config.exit_code,
            report,
        )
        # stderr too: the logger may itself be part of what is stuck
        print(report, file=sys.stderr, flush=True)
        self._exit_fn(self.config.exit_code)
        return True  # only reachable with an injected exit_fn

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_interval_seconds):
            if self.check():
                return
