"""Per-component colored loggers.

Capability parity with the reference's ``areal/utils/logging.py`` (colored
per-component loggers); implementation is our own minimal stdlib setup.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s.%(msecs)03d %(name)s %(levelname)s: %(message)s"
_DATE_FORMAT = "%Y%m%d-%H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[41m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelname, "")
            if color:
                return f"{color}{msg}{_RESET}"
        return msg


_configured: set[str] = set()


def getLogger(name: str = "areal_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if name not in _configured:
        _configured.add(name)
        logger.setLevel(os.environ.get("AREAL_TPU_LOG_LEVEL", "INFO").upper())
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_ColorFormatter(_FORMAT, _DATE_FORMAT))
            logger.addHandler(handler)
        logger.propagate = False
    return logger
