"""ctypes bindings to the native host runtime (csrc/areal_host.cpp).

Compiled on demand with g++ into ``<repo>/build/libareal_host.so`` (one-time,
cached, guarded by an mtime check against the source). Every entry point has a
pure-Python fallback — ``available()`` is False when no toolchain exists and
callers in utils/datapack transparently degrade.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("native")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "csrc", "areal_host.cpp")
_OUT_DIR = os.path.join(_REPO, "build")
_SO = os.path.join(_OUT_DIR, "libareal_host.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _build() -> str | None:
    if os.path.isfile(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    os.makedirs(_OUT_DIR, exist_ok=True)
    # per-pid temp: launcher-spawned processes may build concurrently, and
    # os.replace makes the final install atomic either way
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        logger.info("built native host library at %s", _SO)
        return _SO
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        logger.warning("native build failed (%s); using Python fallbacks", e)
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # once-per-process double-checked init: the lock's whole job is to
        # make concurrent first callers wait for the single cc invocation
        # instead of racing their own builds
        so = _build()  # arealint: disable=await-under-lock
        if so is None:
            return None
        try:
            lib = _bind(ctypes.CDLL(so))
        except OSError as e:
            logger.warning("native library load failed (%s); Python fallbacks", e)
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.areal_ffd_allocate.restype = ctypes.c_int64
    lib.areal_ffd_allocate.argtypes = [_I64P, ctypes.c_int64, ctypes.c_int64, _I64P]
    lib.areal_partition_balanced.restype = ctypes.c_int64
    lib.areal_partition_balanced.argtypes = [_I64P, ctypes.c_int64, ctypes.c_int64, _I64P]
    lib.areal_merge_intervals.restype = ctypes.c_int64
    lib.areal_merge_intervals.argtypes = [_I64P, _I64P, ctypes.c_int64]
    lib.areal_slice_intervals_f32.restype = None
    lib.areal_slice_intervals_f32.argtypes = [_F32P, _I64P, _I64P, ctypes.c_int64, _F32P]
    lib.areal_set_intervals_f32.restype = None
    lib.areal_set_intervals_f32.argtypes = [_F32P, _I64P, _I64P, ctypes.c_int64, _F32P]
    lib.areal_gae_1d_packed_f32.restype = None
    lib.areal_gae_1d_packed_f32.argtypes = [
        _F32P, _F32P, _I64P, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, _F32P,
    ]
    return lib


def available() -> bool:
    return _load() is not None


def ffd_group_ids(sizes: np.ndarray, capacity: int) -> tuple[int, np.ndarray] | None:
    """Native FFD core: (n_bins, group_ids) or None if unavailable.
    Raises ValueError when an item exceeds capacity (parity with Python)."""
    lib = _load()
    if lib is None:
        return None
    sizes = np.ascontiguousarray(sizes, np.int64)
    out = np.empty(len(sizes), np.int64)
    nb = lib.areal_ffd_allocate(sizes, len(sizes), capacity, out)
    if nb < 0:
        raise ValueError(
            f"Item of size {int(sizes.max())} exceeds bin capacity {capacity}"
        )
    return int(nb), out


def partition_group_ids(sizes: np.ndarray, k: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    sizes = np.ascontiguousarray(sizes, np.int64)
    out = np.empty(len(sizes), np.int64)
    rc = lib.areal_partition_balanced(sizes, len(sizes), k, out)
    if rc < 0:
        raise ValueError("k must be positive")
    return out


def merge_intervals(starts: np.ndarray, ends: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Merged [start, end) intervals (sorted). Python fallback included."""
    lib = _load()
    starts = np.ascontiguousarray(starts, np.int64).copy()
    ends = np.ascontiguousarray(ends, np.int64).copy()
    if lib is not None:
        m = lib.areal_merge_intervals(starts, ends, len(starts))
        return starts[:m], ends[:m]
    iv = sorted(zip(starts.tolist(), ends.tolist()))
    ms, me = [], []
    for s, e in iv:
        if ms and s <= me[-1]:
            me[-1] = max(me[-1], e)
        else:
            ms.append(s)
            me.append(e)
    return np.asarray(ms, np.int64), np.asarray(me, np.int64)


def slice_intervals(src: np.ndarray, starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Gather [start, end) slices of a flat fp32 buffer, packed back-to-back
    (reference csrc/interval_op slice_intervals — used for flattened-param
    staging in weight transfer)."""
    src = np.ascontiguousarray(src, np.float32)
    starts = np.ascontiguousarray(starts, np.int64)
    ends = np.ascontiguousarray(ends, np.int64)
    total = int((ends - starts).sum())
    out = np.empty(total, np.float32)
    lib = _load()
    if lib is not None:
        lib.areal_slice_intervals_f32(src, starts, ends, len(starts), out)
        return out
    off = 0
    for s, e in zip(starts, ends):
        out[off : off + (e - s)] = src[s:e]
        off += e - s
    return out


def set_intervals(dst: np.ndarray, starts: np.ndarray, ends: np.ndarray, src: np.ndarray):
    """Scatter packed fp32 values into [start, end) slices of dst, in place."""
    assert dst.dtype == np.float32 and dst.flags["C_CONTIGUOUS"]
    starts = np.ascontiguousarray(starts, np.int64)
    ends = np.ascontiguousarray(ends, np.int64)
    src = np.ascontiguousarray(src, np.float32)
    lib = _load()
    if lib is not None:
        lib.areal_set_intervals_f32(dst, starts, ends, len(starts), src)
        return
    off = 0
    for s, e in zip(starts, ends):
        dst[s:e] = src[off : off + (e - s)]
        off += e - s


def gae_1d_packed(
    rewards: np.ndarray,
    values: np.ndarray,
    cu_seqlens: np.ndarray,
    gamma: float,
    lam: float,
) -> np.ndarray:
    """Host GAE over packed sequences (cuGAE gae_1d_nolp_misalign semantics:
    values carries one bootstrap entry extra per sequence)."""
    rewards = np.ascontiguousarray(rewards, np.float32)
    values = np.ascontiguousarray(values, np.float32)
    cu = np.ascontiguousarray(cu_seqlens, np.int64)
    n_seqs = len(cu) - 1
    out = np.empty(len(rewards), np.float32)
    lib = _load()
    if lib is not None:
        lib.areal_gae_1d_packed_f32(rewards, values, cu, n_seqs, gamma, lam, out)
        return out
    for s in range(n_seqs):
        r0, r1 = int(cu[s]), int(cu[s + 1])
        val = values[r0 + s : r1 + s + 1]
        carry = 0.0
        for t in range(r1 - r0 - 1, -1, -1):
            delta = rewards[r0 + t] + gamma * val[t + 1] - val[t]
            carry = delta + gamma * lam * carry
            out[r0 + t] = carry
    return out
