"""RL training-health observatory: the algorithm plane's telemetry + sentinel.

PRs 8-9 made the *systems* planes explainable (tracing, ``/metrics``,
goodput/MFU); this module lights up the *algorithm* plane. Decoupled-PPO's
importance ratios, clip fractions, per-token staleness mix, and reward /
entropy / length distributions are computed inside the loss and discarded —
exactly the signals AReaL's staleness-controlled async design depends on. An
async run can silently diverge (entropy collapse, ratio blow-up, degenerate
repetition, NaN loss) and, before this module, nothing noticed until the
checkpoint was already garbage.

Two halves:

**Distribution telemetry** — once per train step, from host-side numpy the
update path already holds (never an extra forward, never per-token python in
a hot loop):

- staleness: per-token ``versions`` vs the current weight version — lag
  histogram, mean/max/p95, and the version-mix fraction (sequences whose
  generated tokens span >1 weight version — the in-flight-weight-swap
  trainability signal, ROADMAP item 3);
- ratios: ``exp(prox_logp - behav_logp)`` per token via the exact numpy
  mirror of the jitted loss stats (:func:`areal_tpu.utils.functional.
  ppo_loss_stats_host`) — histogram, p99/max, PPO clip fraction, dual-clip
  fraction, and the behav-cap trigger fraction (tokens the decoupled
  objective drops);
- rewards: raw vs shaped-and-clipped distributions + clipped fraction;
- entropy/KL: Monte-Carlo entropy estimates (mean ``-logprob`` of sampled
  tokens under the behavior and current policies — E_{a~pi}[-log pi(a)] is
  H(pi), so a collapse toward deterministic outputs drives this to 0) and
  the configured k1/k2/k3 staleness-KL estimate;
- generation shape: length distribution, truncation (no-EOS) rate, and a
  cheap degenerate-output detector (:func:`degenerate_output_stats` — max
  n-gram loop fraction + EOS-absence rate), wired at the
  ``WorkflowExecutor.wait`` batch boundary.

Everything exports three ways: ``areal_rl_*`` instruments on the PR 8
metrics registry (``/metrics`` + periodic StatsLogger registry export),
``rl_health/*`` scalars returned from :meth:`RLHealthMonitor.end_step` for
the step's StatsLogger row, and one ``rl_health`` event on the PR 9
``train.step`` span (the Perfetto cross-plane join).

**Anomaly sentinel** — a declarative rule table evaluated once per step
with hysteresis (``consecutive`` breached evaluations before firing; a
fired rule latches until its condition clears, so a persistent breach
fires once, not every step): non-finite loss/grad, entropy below floor,
ratio p99 past cap, staleness p95 past threshold, reward collapse /
flatline, repetition spike. A firing rule

1. bumps ``areal_rl_anomaly_total{rule}``,
2. writes a flight-recorder ``anomaly`` entry holding the full
   offending-step stats (the ``rl_health`` channel ring holds the recent
   steps leading up to it) and dumps the recorder atomically,
3. drives the configured guardrail: ``warn`` (log only),
   ``pause_rollout`` (stop feeding new episodes via
   ``WorkflowExecutor.pause`` while the operator looks), or ``halt``
   (raise :class:`RLHealthHalt` BEFORE the step's checkpoint commits —
   a poisoned step must never become the resume point).

Chaos: the sentinel's detection path is rehearsed by deterministic signal
faults (``AREAL_CHAOS_RL``, :func:`areal_tpu.utils.chaos.rl_fault`) that
corrupt the observed snapshot — never the training math — at an exact
step, so tests pin step-exact detection, dump contents, and guardrails.

Cost contract: disabled (``rl_health.enabled=false``) the monitor is
``None`` and every hot-path site pays only an ``is not None`` check
(code-inspection pinned, like the chaos/tracing hooks); enabled, all work
runs once per STEP on arrays the update already materialized.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("rl_health")

#: flight-recorder channels
HEALTH_CHANNEL = "rl_health"
ANOMALY_CHANNEL = "anomaly"

GUARDRAIL_ACTIONS = ("warn", "pause_rollout", "halt")

#: importance-ratio histogram buckets (ratio 1.0 = perfectly on-policy)
RATIO_BUCKETS = (
    0.125, 0.25, 0.5, 0.8, 0.9, 0.95, 1.0, 1.05, 1.1, 1.25, 1.5, 2.0,
    4.0, 8.0,
)
#: per-token staleness (weight-version lag) buckets
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
#: task-reward buckets (shaped rewards are clipped into a few units)
REWARD_BUCKETS = (-10.0, -5.0, -2.0, -1.0, -0.5, 0.0, 0.5, 1.0, 2.0, 5.0, 10.0)
#: generated-length buckets (tokens)
GEN_LEN_BUCKETS = (
    16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0,
    8192.0, 16384.0, 32768.0,
)


class RLHealthHalt(RuntimeError):
    """The ``halt`` guardrail: an anomaly rule fired with action ``halt``.
    Raised from :meth:`RLHealthMonitor.end_step` — which the trainer loop
    calls BEFORE the stats commit and checkpoint — so the poisoned step's
    state never becomes the resume point. The flight-recorder ``anomaly``
    dump has already been written when this propagates."""


# ---------------------------------------------------------------------------
# degenerate-output detector (host-side, once per rollout batch)
# ---------------------------------------------------------------------------


def _tail_loop_fraction(gen: np.ndarray, max_ngram: int = 8) -> float:
    """Fraction of a generated-token sequence covered by consecutive
    trailing repeats of its final n-gram, maximized over n in [1,
    ``max_ngram``]. A healthy completion scores ~0; a model stuck emitting
    "the the the" or a looping sentence scores toward 1.

    Fully vectorized per n (shifted-equality + trailing-True run length):
    O(len * max_ngram) numpy with NO data-dependent python loop, so the
    cost is the same for healthy and fully-degenerate sequences — the
    detector must stay cheap precisely when outputs are at their worst.
    """
    ln = int(gen.shape[0])
    best = 0.0
    for n in range(1, min(max_ngram, ln // 2) + 1):
        # eq[i] == True  <=>  gen[i] == gen[i+n]; a trailing all-True run
        # of length k means the last k+n tokens are periodic with period n
        eq = gen[n:] == gen[:-n]
        false_idx = np.flatnonzero(~eq)
        k = (eq.shape[0] - 1 - false_idx[-1]) if false_idx.size else eq.shape[0]
        repeats = (k + n) // n  # aligned whole copies of the final n-gram
        if repeats >= 2:
            best = max(best, (repeats * n) / ln)
    return best


def degenerate_output_stats(
    input_ids: np.ndarray,
    loss_mask: np.ndarray,
    attention_mask: np.ndarray,
    max_ngram: int = 8,
) -> dict[str, np.ndarray | float]:
    """Per-batch degenerate-output signals over the GENERATED tokens
    (``loss_mask == 1``): per-sequence max n-gram loop fraction, generated
    lengths, and the no-EOS (row completely full => truncated at max
    length, the convention the actor's ``no_eos_ratios`` stat uses) flags.
    """
    ids = np.asarray(input_ids)
    lm = np.asarray(loss_mask).astype(bool)
    attn = np.asarray(attention_mask)
    bs, width = ids.shape
    loop_frac = np.zeros(bs, np.float32)
    gen_lens = np.zeros(bs, np.int64)
    for i in range(bs):
        gen = ids[i][lm[i] & (attn[i] > 0)]
        gen_lens[i] = gen.shape[0]
        if gen.shape[0] >= 2:
            loop_frac[i] = _tail_loop_fraction(gen, max_ngram)
    eos_absent = attn.sum(-1) == width
    return dict(
        loop_frac=loop_frac,
        gen_lens=gen_lens,
        eos_absent=eos_absent,
        repetition_frac=float(loop_frac.mean()) if bs else 0.0,
        repetition_max=float(loop_frac.max()) if bs else 0.0,
        eos_absence_rate=float(eos_absent.mean()) if bs else 0.0,
        gen_len_mean=float(gen_lens.mean()) if bs else 0.0,
        gen_len_p95=float(np.percentile(gen_lens, 95)) if bs else 0.0,
    )


# ---------------------------------------------------------------------------
# sentinel rules
# ---------------------------------------------------------------------------


class _Rule:
    """One declarative anomaly rule: breached(snap) over the step snapshot;
    ``consecutive`` is the hysteresis requirement (None = config default)."""

    __slots__ = ("name", "breached", "consecutive", "describe")

    def __init__(self, name, breached, consecutive=None, describe=""):
        self.name = name
        self.breached = breached
        self.consecutive = consecutive
        self.describe = describe


def _nonfinite(v) -> bool:
    return v is not None and not math.isfinite(float(v))


def build_rules(cfg) -> list[_Rule]:
    """The sentinel's rule table, thresholds from :class:`RLHealthConfig`.
    Every predicate reads the per-step snapshot; a signal absent from the
    snapshot (e.g. no rollout batch observed this step) never breaches."""

    def _gt(key, thr):
        def f(s):
            v = s.get(key)
            return v is not None and math.isfinite(float(v)) and float(v) > thr

        return f

    def _entropy_floor(s):
        v = s.get("entropy")
        return v is not None and math.isfinite(float(v)) and float(v) < cfg.entropy_floor

    def _non_finite(s):
        return _nonfinite(s.get("loss")) or _nonfinite(s.get("grad_norm"))

    def _reward_collapse(s):
        if s.get("reward_window_full") and (
            s.get("reward_window_std", math.inf) <= cfg.reward_std_floor
        ):
            return True
        drop = cfg.reward_collapse_drop
        if drop > 0 and s.get("reward_mean") is not None:
            trailing = s.get("reward_trailing_mean")
            if trailing is not None and float(s["reward_mean"]) < trailing - drop:
                return True
        return False

    return [
        _Rule(
            "non_finite_loss", _non_finite, consecutive=1,
            describe="loss or grad_norm is NaN/Inf",
        ),
        _Rule(
            "entropy_floor", _entropy_floor,
            describe=f"entropy estimate < {cfg.entropy_floor}",
        ),
        _Rule(
            "ratio_blowup", _gt("ratio_p99", cfg.ratio_p99_cap),
            describe=f"importance-ratio p99 > {cfg.ratio_p99_cap}",
        ),
        _Rule(
            "staleness_spike", _gt("staleness_p95", cfg.staleness_p95_max),
            describe=f"per-token staleness p95 > {cfg.staleness_p95_max}",
        ),
        _Rule(
            "reward_collapse", _reward_collapse,
            describe="reward flatlined (window std ~ 0) or dropped sharply",
        ),
        _Rule(
            "repetition_spike",
            _gt("repetition_frac", cfg.repetition_max_frac),
            describe=(
                "mean n-gram loop fraction of generated tokens > "
                f"{cfg.repetition_max_frac}"
            ),
        ),
    ]


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

#: snapshot keys exported as gauges (``areal_rl_<key>``) and StatsLogger
#: scalars (``rl_health/<key>``); help strings double as the signal catalog
SCALAR_SIGNALS = {
    "ratio_mean": "masked mean importance ratio exp(prox - behav)",
    "ratio_p99": "importance-ratio p99 over valid tokens",
    "ratio_max": "importance-ratio max over valid tokens",
    "clip_frac": "fraction of valid tokens where the PPO clip binds",
    "dual_clip_frac": "fraction of valid tokens where the dual clip binds",
    "behav_cap_frac": "fraction of valid tokens past behav_imp_weight_cap",
    "kl": "masked-mean staleness KL estimate (configured k1/k2/k3)",
    "entropy": "MC entropy estimate of the current policy (mean -prox_logp)",
    "entropy_behav": "MC entropy estimate of the behavior policy",
    "adv_mean": "masked mean advantage",
    "adv_std": "masked advantage standard deviation",
    "staleness_mean": "mean per-token weight-version lag",
    "staleness_max": "max per-token weight-version lag",
    "staleness_p95": "p95 per-token weight-version lag",
    "version_mix_frac": "fraction of sequences spanning >1 weight version",
    "reward_mean": "mean raw task reward",
    "reward_std": "std of raw task rewards",
    "reward_clipped_mean": "mean shaped+clipped reward",
    "reward_clipped_frac": "fraction of rewards hitting the clip bound",
    "repetition_frac": "mean max n-gram loop fraction of generated tokens",
    "repetition_max": "max per-sequence n-gram loop fraction",
    "eos_absence_rate": "fraction of sequences truncated without EOS",
    "gen_len_mean": "mean generated length (tokens)",
    "gen_len_p95": "p95 generated length (tokens)",
    "loss": "train loss (as reported by the engine)",
    "grad_norm": "global grad norm (as reported by the engine)",
}


class RLHealthMonitor:
    """Per-step RL-health snapshot assembly + sentinel evaluation.

    Observation methods (``observe_rollout_batch`` from the executor's
    wait boundary, ``observe_train_batch`` / ``note_rewards`` /
    ``note_train_result`` from the PPO actor) stage signals into the
    current step's snapshot; :meth:`end_step` closes the window: applies
    chaos faults, evaluates the rule table with hysteresis, exports
    metrics/ring/status, drives guardrails, and returns the
    ``rl_health/*`` scalar row for the StatsLogger commit.
    """

    def __init__(
        self,
        config,
        *,
        registry=None,
        recorder=None,
        pause_fn=None,
        clock=time.time,
    ):
        self.config = config
        self._clock = clock
        self._pause_fn = pause_fn
        self._lock = threading.Lock()
        self._snap: dict = {}
        self._reward_window: deque = deque(
            maxlen=max(2, int(config.reward_window_steps))
        )
        self._streaks: dict[str, int] = {}
        self._latched: set[str] = set()
        self.last_anomaly: dict | None = None
        self.anomalies_fired = 0
        # latched by the pause_rollout guardrail. The trainer loops call
        # pause()/resume() around every weight push — an unconditional
        # resume there would silently undo the guardrail one step later,
        # so the examples gate their resume on this flag. Cleared only by
        # an explicit resume_rollout() (operator decision).
        self.rollout_paused = False

        for name, act in dict(config.rule_actions).items():
            if act not in GUARDRAIL_ACTIONS:
                raise ValueError(
                    f"rl_health.rule_actions[{name!r}] = {act!r}; must be "
                    f"one of {GUARDRAIL_ACTIONS}"
                )
        if config.action not in GUARDRAIL_ACTIONS:
            raise ValueError(
                f"rl_health.action = {config.action!r}; must be one of "
                f"{GUARDRAIL_ACTIONS}"
            )
        self._rules = build_rules(config)

        if recorder is None:
            from areal_tpu.utils import flight_recorder

            recorder = flight_recorder.DEFAULT_RECORDER
        self._recorder = recorder
        recorder.channel(HEALTH_CHANNEL, capacity=int(config.ring_steps))
        recorder.channel(ANOMALY_CHANNEL)

        if registry is None:
            from areal_tpu.utils import metrics

            registry = metrics.DEFAULT_REGISTRY
        self._registry = registry
        self._ratio_hist = registry.histogram(
            "areal_rl_importance_ratio",
            "per-token importance ratio exp(prox_logp - behav_logp)",
            buckets=RATIO_BUCKETS,
        )
        self._behav_hist = registry.histogram(
            "areal_rl_behav_ratio",
            "behavior importance weights the decoupled objective actually "
            "applies (cap-excluded tokens dropped)",
            buckets=RATIO_BUCKETS,
        )
        self._staleness_hist = registry.histogram(
            "areal_rl_staleness",
            "per-token weight-version lag (current - token version)",
            buckets=STALENESS_BUCKETS,
        )
        self._reward_hist = registry.histogram(
            "areal_rl_reward",
            "task reward distribution, raw vs shaped+clipped",
            labels=("kind",),
            buckets=REWARD_BUCKETS,
        )
        self._gen_len_hist = registry.histogram(
            "areal_rl_gen_len",
            "generated tokens per sequence",
            buckets=GEN_LEN_BUCKETS,
        )
        self._gauges = {
            key: registry.gauge(f"areal_rl_{key}", help_)
            for key, help_ in SCALAR_SIGNALS.items()
        }
        self._anomaly_c = registry.counter(
            "areal_rl_anomaly_total",
            "sentinel rules fired (latched: once per sustained breach)",
            labels=("rule",),
        )

    @classmethod
    def from_config(cls, config, **kwargs) -> "RLHealthMonitor | None":
        """None when disabled — hot-path call sites then pay only an
        ``is not None`` check (the chaos-hook discipline)."""
        if config is None or not getattr(config, "enabled", True):
            return None
        return cls(config, **kwargs)

    # ------------------------------------------------------------ observing

    def observe_rollout_batch(self, batch: dict) -> None:
        """Degenerate-output + generation-shape signals from one collected
        rollout batch (called at the ``WorkflowExecutor.wait`` boundary)."""
        try:
            ids = batch.get("input_ids")
            lm = batch.get("loss_mask")
            attn = batch.get("attention_mask")
            if ids is None or attn is None:
                return
            if lm is None:
                lm = np.asarray(attn)
            d = degenerate_output_stats(np.asarray(ids), lm, np.asarray(attn))
            self._gen_len_hist.observe_many(d["gen_lens"])
            with self._lock:
                for k in (
                    "repetition_frac", "repetition_max", "eos_absence_rate",
                    "gen_len_mean", "gen_len_p95",
                ):
                    self._snap[k] = d[k]
        except Exception:
            # telemetry must never take down the rollout path
            logger.exception("observe_rollout_batch failed")

    def observe_train_batch(
        self, data: dict, current_version: int, actor_config
    ) -> None:
        """Ratio/staleness/entropy/KL/advantage signals from the update
        batch, AFTER ``compute_advantages`` aligned everything to the
        next-token convention (``logprobs`` = behavior policy,
        ``prox_logp`` = current policy, both masked by ``loss_mask``)."""
        try:
            self._observe_train_batch(data, current_version, actor_config)
        except Exception:
            logger.exception("observe_train_batch failed")

    def _observe_train_batch(self, data, current_version, cfg) -> None:
        from areal_tpu.utils.data import KLEstimator
        from areal_tpu.utils.functional import ppo_loss_stats_host

        mask = np.asarray(data["loss_mask"]).astype(bool)
        n = max(int(mask.sum()), 1)
        old = np.asarray(data["logprobs"], np.float32)
        prox = np.asarray(data.get("prox_logp", old), np.float32)
        adv = np.asarray(data.get("advantages", np.zeros_like(old)), np.float32)
        snap: dict = {"tokens": float(n)}

        # realized importance ratio of the batch about to be trained:
        # exp(current - behavior). The mirror call treats the BEHAVIOR
        # logprobs as the proximal baseline so clip/dual-clip masks measure
        # how much of this batch already sits outside the trust region
        # before the first minibatch moves the weights (the decoupled
        # loss's own ratio is 1 by construction at that point).
        stats = ppo_loss_stats_host(
            logprobs=prox,
            proximal_logprobs=old,
            old_logprobs=old,
            advantages=adv,
            loss_mask=mask,
            eps_clip=cfg.eps_clip,
            eps_clip_higher=getattr(cfg, "eps_clip_higher", None),
            c_clip=getattr(cfg, "c_clip", None),
            behav_imp_weight_cap=None,
        )
        ratio = stats["importance_weight"][mask]
        snap["ratio_mean"] = float(ratio.mean())
        snap["ratio_p99"] = float(np.percentile(ratio, 99))
        snap["ratio_max"] = float(ratio.max())
        snap["clip_frac"] = float(stats["clip_mask"].sum() / n)
        snap["dual_clip_frac"] = float(stats["dual_clip_mask"].sum() / n)
        cap = getattr(cfg, "behav_imp_weight_cap", None)
        snap["behav_cap_frac"] = (
            float((ratio > cap).sum() / n) if cap is not None else 0.0
        )
        self._ratio_hist.observe_many(ratio)
        # the behav-ratio distribution is the same exp(prox - behav) with
        # the cap applied — the weights the decoupled objective actually
        # multiplies into the loss (cap-excluded tokens contribute 0)
        self._behav_hist.observe_many(
            ratio[ratio <= cap] if cap is not None else ratio
        )

        kl_est = KLEstimator(getattr(cfg, "kl_estimator", "k1"))
        snap["kl"] = float((kl_est(prox, old) * mask).sum() / n)
        snap["entropy"] = float((-prox * mask).sum() / n)
        snap["entropy_behav"] = float((-old * mask).sum() / n)
        mv = adv[mask]
        if mv.size:
            snap["adv_mean"] = float(mv.mean())
            snap["adv_std"] = float(mv.std())

        versions = data.get("versions")
        if versions is not None:
            v = np.asarray(versions)
            gen = v >= 0  # -1 marks prompt/non-generated tokens
            if gen.any():
                lags = np.maximum(int(current_version) - v[gen], 0).astype(
                    np.float64
                )
                snap["staleness_mean"] = float(lags.mean())
                snap["staleness_max"] = float(lags.max())
                snap["staleness_p95"] = float(np.percentile(lags, 95))
                per_seq = [
                    len(np.unique(row[g])) > 1
                    for row, g in zip(v, gen)
                    if g.any()
                ]
                snap["version_mix_frac"] = (
                    float(np.mean(per_seq)) if per_seq else 0.0
                )
                self._staleness_hist.observe_many(lags)
        with self._lock:
            self._snap.update(snap)

    def note_rewards(
        self, raw: np.ndarray, clipped: np.ndarray, clipped_frac: float
    ) -> None:
        """Raw vs shaped-and-clipped reward distributions (from the
        actor's ``compute_advantages`` reward pipeline)."""
        try:
            raw = np.asarray(raw, np.float64).reshape(-1)
            clipped = np.asarray(clipped, np.float64).reshape(-1)
            self._reward_hist.labels(kind="raw").observe_many(raw)
            self._reward_hist.labels(kind="clipped").observe_many(clipped)
            with self._lock:
                self._snap["reward_mean"] = float(raw.mean()) if raw.size else 0.0
                self._snap["reward_std"] = float(raw.std()) if raw.size else 0.0
                self._snap["reward_clipped_mean"] = (
                    float(clipped.mean()) if clipped.size else 0.0
                )
                self._snap["reward_clipped_frac"] = float(clipped_frac)
        except Exception:
            logger.exception("note_rewards failed")

    def note_train_result(
        self, loss=None, grad_norm=None, update_successful=None
    ) -> None:
        """Engine-reported loss/grad scalars, once per minibatch; a
        non-finite value sticks for the step (one NaN minibatch is the
        anomaly even if later minibatches look sane)."""
        with self._lock:
            for key, v in (("loss", loss), ("grad_norm", grad_norm)):
                if v is None:
                    continue
                prev = self._snap.get(key)
                if prev is None or math.isfinite(float(prev)):
                    self._snap[key] = float(v)
            if update_successful is not None:
                self._snap["update_successful"] = float(update_successful)

    # ------------------------------------------------------------- stepping

    def end_step(self, global_step: int, span=None) -> dict[str, float]:
        """Close the step's observation window: chaos faults, reward-window
        bookkeeping, rule evaluation with hysteresis, metric/ring/status
        export, guardrails. Returns the ``rl_health/*`` StatsLogger row.
        Raises :class:`RLHealthHalt` when a fired rule's action is
        ``halt`` (after the anomaly dump has been written)."""
        with self._lock:
            snap, self._snap = self._snap, {}

        self._update_reward_window(snap)
        self._apply_chaos(snap)
        fired = self._evaluate_rules(snap)

        for key, g in self._gauges.items():
            v = snap.get(key)
            if v is not None and math.isfinite(float(v)):
                g.set(float(v))

        compact = {
            k: v for k, v in snap.items() if isinstance(v, (int, float))
        }
        self._recorder.record(
            HEALTH_CHANNEL, "step", step=int(global_step), **compact
        )
        if span is not None:
            span.event(
                "rl_health",
                step=int(global_step),
                anomalies=",".join(r.name for r in fired),
                **{
                    k: round(float(compact[k]), 6)
                    for k in (
                        "entropy", "ratio_p99", "staleness_p95",
                        "reward_mean", "clip_frac", "repetition_frac",
                        "loss",
                    )
                    if k in compact
                },
            )

        row = {f"rl_health/{k}": float(v) for k, v in compact.items()}
        row["rl_health/anomaly"] = float(bool(fired))

        halt_rules: list[str] = []
        pause_rules: list[str] = []
        for rule in fired:
            self.anomalies_fired += 1
            action = dict(self.config.rule_actions).get(
                rule.name, self.config.action
            )
            self.last_anomaly = {
                "rule": rule.name,
                "step": int(global_step),
                "t": self._clock(),
                "action": action,
            }
            self._anomaly_c.labels(rule=rule.name).inc()
            self._recorder.record(
                ANOMALY_CHANNEL,
                "rule_fired",
                rule=rule.name,
                step=int(global_step),
                action=action,
                streak=self._streaks.get(rule.name, 0),
                describe=rule.describe,
                stats=compact,
            )
            # immediate atomic dump: the offending-step evidence must not
            # depend on the process surviving to its next death-path dump
            self._recorder.dump(f"rl_anomaly_{rule.name}")
            logger.warning(
                "RL-health anomaly %r at step %d (%s); guardrail action: "
                "%s; offending stats: %s",
                rule.name,
                global_step,
                rule.describe,
                action,
                {k: round(v, 4) for k, v in sorted(compact.items())},
            )
            if action == "halt":
                halt_rules.append(rule.name)
            elif action == "pause_rollout":
                pause_rules.append(rule.name)

        self._publish_status(global_step, compact)

        if pause_rules:
            logger.warning(
                "pausing rollout submission (rules: %s); resume manually "
                "or restart once the cause is addressed",
                ",".join(pause_rules),
            )
            self.rollout_paused = True
            if self._pause_fn is not None:
                self._pause_fn()
        if halt_rules:
            raise RLHealthHalt(
                f"RL-health guardrail halt at step {global_step} "
                f"(rules: {','.join(halt_rules)}); the anomaly flight dump "
                "is on disk and this step's checkpoint was NOT committed"
            )
        return row

    def resume_rollout(self) -> None:
        """Clear the pause_rollout latch (an explicit operator/driver
        decision — the guardrail never un-pauses on its own). The caller
        resumes the executor itself."""
        self.rollout_paused = False

    # ------------------------------------------------------------ internals

    def _update_reward_window(self, snap: dict) -> None:
        rm = snap.get("reward_mean")
        if rm is not None and math.isfinite(float(rm)):
            if len(self._reward_window):
                snap["reward_trailing_mean"] = float(
                    np.mean(self._reward_window)
                )
            self._reward_window.append(float(rm))
            snap["reward_window_full"] = (
                len(self._reward_window) == self._reward_window.maxlen
            )
            snap["reward_window_std"] = float(np.std(self._reward_window))

    def _apply_chaos(self, snap: dict) -> None:
        """Deterministic signal faults (AREAL_CHAOS_RL): corrupt the
        OBSERVED snapshot so the sentinel's detection/guardrail path is
        exercised end to end without touching the training math."""
        from areal_tpu.utils.chaos import rl_fault

        if rl_fault("nan_loss"):
            snap["loss"] = float("nan")
        if rl_fault("entropy_collapse"):
            snap["entropy"] = 0.0
        if rl_fault("staleness_spike"):
            spike = float(self.config.staleness_p95_max) * 10.0 + 100.0
            snap["staleness_p95"] = spike
            snap["staleness_max"] = max(snap.get("staleness_max", 0.0), spike)
        if rl_fault("ratio_blowup"):
            snap["ratio_p99"] = float(self.config.ratio_p99_cap) * 10.0
        if rl_fault("reward_flatline"):
            snap["reward_window_full"] = True
            snap["reward_window_std"] = 0.0
        if rl_fault("repetition_spike"):
            snap["repetition_frac"] = 1.0

    def _evaluate_rules(self, snap: dict) -> list[_Rule]:
        """Hysteresis: a rule fires after ``consecutive`` breached
        evaluations, then latches — no re-fire while the breach persists;
        clearing resets both streak and latch."""
        fired = []
        default_consec = max(1, int(self.config.consecutive))
        for rule in self._rules:
            breached = bool(rule.breached(snap))
            if not breached:
                self._streaks[rule.name] = 0
                self._latched.discard(rule.name)
                continue
            self._streaks[rule.name] = self._streaks.get(rule.name, 0) + 1
            need = rule.consecutive or default_consec
            if (
                self._streaks[rule.name] >= need
                and rule.name not in self._latched
            ):
                self._latched.add(rule.name)
                fired.append(rule)
        return fired

    def _publish_status(self, global_step: int, compact: dict) -> None:
        """Compact status JSON for ``areal-tpu-top`` via name_resolve
        (best-effort: discovery being down must never fail a train step)."""
        cfg = self.config
        if not cfg.publish_status or not cfg.experiment_name:
            return
        payload = {
            "step": int(global_step),
            "t": self._clock(),
            "last_anomaly": self.last_anomaly,
            "anomalies_fired": self.anomalies_fired,
            **{
                k: round(float(compact[k]), 6)
                for k in (
                    "entropy", "ratio_p99", "staleness_p95", "staleness_mean",
                    "reward_mean", "clip_frac", "repetition_frac",
                    "eos_absence_rate", "version_mix_frac",
                )
                if k in compact
            },
        }
        try:
            from areal_tpu.utils import name_resolve, names

            name_resolve.add(
                names.rl_health(cfg.experiment_name, cfg.trial_name),
                json.dumps(payload),
                replace=True,
                delete_on_exit=False,
            )
        except Exception:
            logger.debug("rl_health status publish failed", exc_info=True)
