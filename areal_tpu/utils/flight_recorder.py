"""Crash flight recorder: per-subsystem ring buffers of recent structured
events, dumped atomically at the moment of death.

PR 3/4 built the chaos harness (injected crashes, watchdog, SIGTERM
drains) — but a postmortem still started from logs alone: the watchdog's
stack dump says WHERE the trainer wedged, not what the last 200 requests,
breaker transitions, weight commits, and admission decisions looked like
on the way in. Each subsystem records its recent history into a bounded
ring here (``record("breaker", "open", addr=...)`` — a deque append, no
I/O, safe on warm paths), and the three death paths dump every ring as
one JSON file via the PR 4 atomic write helpers:

- **watchdog timeout** (exit 43): ``Watchdog.check`` dumps before
  ``os._exit`` — evidence survives the hard exit;
- **InjectedCrash**: ``utils/chaos.crash_point`` dumps before raising,
  so every chaos-harness kill leaves the same artifact a real one would;
- **SIGTERM / graceful drain**: ``RecoverHandler.graceful_shutdown``
  dumps next to the recover checkpoint.

Dumps are best-effort by design: a recorder failure must never turn a
clean drain into a crash (every dump path swallows and logs).

The default recorder is process-global (subsystems should not need
plumbing to leave evidence); ``clock`` is injectable for tests.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from areal_tpu.utils import logging

logger = logging.getLogger("flight_recorder")

#: override the dump directory without config plumbing (launcher sets it
#: next to the trial dir); default keeps dumps out of the way but findable
DUMP_DIR_ENV = "AREAL_FLIGHT_RECORDER_DIR"

DEFAULT_CAPACITY = 256


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=time.time):
        self._lock = threading.Lock()
        self._channels: dict[str, deque] = {}  # guarded_by: _lock
        self._capacity = capacity
        self._clock = clock
        self._dump_dir: str | None = None
        self.events_recorded = 0
        self.dumps_written = 0

    # -- recording ------------------------------------------------------

    def channel(self, name: str, capacity: int | None = None) -> deque:
        """Get-or-create a ring. Idempotent; explicit ``capacity`` only
        applies on first creation."""
        with self._lock:
            ch = self._channels.get(name)
            if ch is None:
                ch = self._channels[name] = deque(
                    maxlen=capacity or self._capacity
                )
            return ch

    def record(self, channel: str, kind: str, **fields) -> None:
        """Append one structured event. Cheap enough for warm paths (one
        lock, one dict, one deque append); keep it off token-level hot
        loops. The append holds the lock: snapshot() iterates the rings
        under it, and CPython raises RuntimeError on a deque mutated
        mid-iteration — an unlocked append racing a crash-time dump
        would lose the postmortem exactly when traffic is busiest."""
        ev = {"t": self._clock(), "kind": kind, **fields}
        with self._lock:
            ch = self._channels.get(channel)
            if ch is None:
                ch = self._channels[channel] = deque(maxlen=self._capacity)
            ch.append(ev)
            self.events_recorded += 1

    # -- dumping --------------------------------------------------------

    def set_dump_dir(self, path: str) -> None:
        self._dump_dir = path

    def dump_dir(self) -> str:
        return (
            self._dump_dir
            or os.environ.get(DUMP_DIR_ENV)
            or "/tmp/areal_tpu/flight_recorder"
        )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dumped_at": self._clock(),
                "pid": os.getpid(),
                "events_recorded": self.events_recorded,
                "channels": {
                    name: list(ring)
                    for name, ring in self._channels.items()
                },
            }

    def dump(self, reason: str, path: str | None = None) -> str | None:
        """Atomically write every ring to one JSON file; returns the
        path, or None when the dump failed (best-effort: the recorder
        must never turn a clean exit into a crash)."""
        try:
            from areal_tpu.utils.fs import atomic_write_json

            if path is None:
                d = self.dump_dir()
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d,
                    f"flight_{reason}_{os.getpid()}_"
                    f"{self.dumps_written}.json",
                )
            snap = self.snapshot()
            snap["reason"] = reason
            atomic_write_json(path, snap)
            self.dumps_written += 1
            logger.warning(
                "flight recorder dumped %d event(s) across %d channel(s) "
                "-> %s (reason: %s)",
                snap["events_recorded"],
                len(snap["channels"]),
                path,
                reason,
            )
            return path
        except Exception:
            logger.exception("flight recorder dump failed (reason=%s)", reason)
            return None

    def reset(self) -> None:
        with self._lock:
            self._channels.clear()
            self.events_recorded = 0
            self.dumps_written = 0


DEFAULT_RECORDER = FlightRecorder()

record = DEFAULT_RECORDER.record
dump = DEFAULT_RECORDER.dump
set_dump_dir = DEFAULT_RECORDER.set_dump_dir
