"""Asyncio helpers: strong-reference tracking for fire-and-forget tasks.

The event loop holds only weak references to tasks (CPython bpo-44665 class
of bugs): ``asyncio.create_task(coro)`` whose result is dropped can be
garbage-collected mid-flight, silently killing the coroutine. The rollout
executor keeps its episode tasks in its ``live`` table; anything else that
spawns background work (telemetry flushes, abort fan-outs) should go
through :func:`create_tracked_task`, which parks the task in a module-level
registry until it finishes. The ``untracked-task`` arealint rule flags bare
``asyncio.create_task(...)`` statements that drop the reference.
"""

from __future__ import annotations

import asyncio
from typing import Any, Coroutine

from areal_tpu.utils import logging

logger = logging.getLogger("aio")

# strong refs keeping in-flight fire-and-forget tasks alive; entries remove
# themselves on completion
_BACKGROUND_TASKS: set[asyncio.Task] = set()


def create_tracked_task(
    coro: Coroutine[Any, Any, Any],
    *,
    name: str | None = None,
    log_exceptions: bool = True,
) -> asyncio.Task:
    """``asyncio.create_task`` that cannot be garbage-collected mid-flight.

    The task is held in a module-level set until done. With
    ``log_exceptions`` (default), a failed task logs its exception when it
    completes instead of waiting for the loop's unretrieved-exception
    warning at GC time (which a collected task never reaches).
    """
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _BACKGROUND_TASKS.add(task)
    task.add_done_callback(_on_done if log_exceptions else _BACKGROUND_TASKS.discard)
    return task


def _on_done(task: asyncio.Task) -> None:
    _BACKGROUND_TASKS.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logger.error(
            "background task %r failed: %s", task.get_name(), exc,
            exc_info=exc,
        )


def tracked_task_count() -> int:
    """In-flight tracked tasks (tests / leak diagnostics)."""
    return len(_BACKGROUND_TASKS)


async def cancel_tracked_tasks() -> int:
    """Cancel and await every tracked task (shutdown path); returns how
    many were still in flight."""
    tasks = [t for t in _BACKGROUND_TASKS if not t.done()]
    for t in tasks:
        t.cancel()
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    return len(tasks)
