"""Frequency-controlled checkpoint saver (reference: areal/utils/saver.py:148).

A ``_Timer`` fires on any of epoch/step/second frequencies; ``Saver.save``
checks the timer and writes an HF checkpoint through the engine. The same
timer drives ``Evaluator`` (reference: areal/utils/evaluator.py).
"""

from __future__ import annotations

import os
import time
from typing import Callable

from areal_tpu.api.cli_args import EvaluatorConfig, SaverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.utils import logging

logger = logging.getLogger("saver")


class FreqTimer:
    """Fires when epoch/step/sec frequency is crossed (reference _Timer)."""

    def __init__(
        self,
        freq_epochs: int | None = None,
        freq_steps: int | None = None,
        freq_secs: int | None = None,
    ):
        self.freq_epochs = freq_epochs
        self.freq_steps = freq_steps
        self.freq_secs = freq_secs
        self._last_time = time.monotonic()

    def should_fire(self, step: StepInfo, is_epoch_last_step: bool) -> bool:
        if (
            self.freq_epochs is not None
            and is_epoch_last_step
            and (step.epoch + 1) % self.freq_epochs == 0
        ):
            return True
        if (
            self.freq_steps is not None
            and (step.global_step + 1) % self.freq_steps == 0
        ):
            return True
        if (
            self.freq_secs is not None
            and time.monotonic() - self._last_time >= self.freq_secs
        ):
            return True
        return False

    def reset(self):
        self._last_time = time.monotonic()

    def state_dict(self) -> dict:
        return {"elapsed": time.monotonic() - self._last_time}

    def load_state_dict(self, s: dict):
        self._last_time = time.monotonic() - s.get("elapsed", 0.0)


class Saver:
    def __init__(self, config: SaverConfig, ft_spec, for_recover: bool = False):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )
        self.for_recover = for_recover

    def save_root(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "checkpoints" if self.for_recover else "saves",
        )

    def save(
        self, engine, step: StepInfo, force: bool = False, tokenizer=None
    ) -> str | None:
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return None
        path = os.path.join(
            self.save_root(),
            f"epoch{step.epoch}epochstep{step.epoch_step}globalstep{step.global_step}",
        )
        os.makedirs(path, exist_ok=True)
        engine.save(
            SaveLoadMeta(
                path=path,
                weight_format="hf",
                with_optim=self.for_recover,
                tokenizer=tokenizer,
            )
        )
        self.timer.reset()
        logger.info("saved checkpoint at %s", path)
        return path

    def state_dict(self) -> dict:
        return {"timer": self.timer.state_dict()}

    def load_state_dict(self, s: dict):
        self.timer.load_state_dict(s.get("timer", {}))


class Evaluator:
    """Runs a user eval_fn on the saver-style frequency (reference
    areal/utils/evaluator.py)."""

    def __init__(self, config: EvaluatorConfig, ft_spec):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )

    def evaluate(
        self, eval_fn: Callable[[], None], step: StepInfo, force: bool = False
    ) -> bool:
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return False
        eval_fn()
        self.timer.reset()
        return True

    def state_dict(self) -> dict:
        return {"timer": self.timer.state_dict()}

    def load_state_dict(self, s: dict):
        self.timer.load_state_dict(s.get("timer", {}))
