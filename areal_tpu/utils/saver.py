"""Frequency-controlled checkpoint saver (reference: areal/utils/saver.py:148).

A ``_Timer`` fires on any of epoch/step/second frequencies; ``Saver.save``
checks the timer and writes an HF checkpoint through the engine. The same
timer drives ``Evaluator`` (reference: areal/utils/evaluator.py).
"""

from __future__ import annotations

import os
import re
import shutil
import time
from typing import Callable, Iterable

from areal_tpu.api.cli_args import EvaluatorConfig, SaverConfig
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.utils import logging
from areal_tpu.utils.fs import atomic_write_text

logger = logging.getLogger("saver")

#: checkpoint directory naming scheme; the retention GC parses global_step
#: back out of it to order and select survivors
_CKPT_DIR_RE = re.compile(r"^epoch(\d+)epochstep(\d+)globalstep(\d+)$")

#: name of the atomically updated pointer file in the save root; always
#: names the most recent successfully written checkpoint directory
LATEST_POINTER = "latest"


class FreqTimer:
    """Fires when epoch/step/sec frequency is crossed (reference _Timer)."""

    def __init__(
        self,
        freq_epochs: int | None = None,
        freq_steps: int | None = None,
        freq_secs: int | None = None,
    ):
        self.freq_epochs = freq_epochs
        self.freq_steps = freq_steps
        self.freq_secs = freq_secs
        self._last_time = time.monotonic()

    def should_fire(self, step: StepInfo, is_epoch_last_step: bool) -> bool:
        if (
            self.freq_epochs is not None
            and is_epoch_last_step
            and (step.epoch + 1) % self.freq_epochs == 0
        ):
            return True
        if (
            self.freq_steps is not None
            and (step.global_step + 1) % self.freq_steps == 0
        ):
            return True
        if (
            self.freq_secs is not None
            and time.monotonic() - self._last_time >= self.freq_secs
        ):
            return True
        return False

    def reset(self):
        self._last_time = time.monotonic()

    def state_dict(self) -> dict:
        return {"elapsed": time.monotonic() - self._last_time}

    def load_state_dict(self, s: dict):
        self._last_time = time.monotonic() - s.get("elapsed", 0.0)


class Saver:
    def __init__(self, config: SaverConfig, ft_spec, for_recover: bool = False):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )
        self.for_recover = for_recover
        #: last checkpoint this saver wrote (rides the RunState so recover
        #: info can protect it from retention GC)
        self.last_save_path: str | None = None

    def save_root(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "checkpoints" if self.for_recover else "saves",
        )

    def save(
        self,
        engine,
        step: StepInfo,
        force: bool = False,
        tokenizer=None,
        protect: Iterable[str] = (),
    ) -> str | None:
        """Write a checkpoint when the timer fires, update the ``latest``
        pointer atomically, and run retention GC. ``protect`` names
        checkpoints the GC must keep regardless of retention policy (the
        path the recover info references — deleting it would strand the
        next recovery run)."""
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return None
        path = os.path.join(
            self.save_root(),
            f"epoch{step.epoch}epochstep{step.epoch_step}globalstep{step.global_step}",
        )
        os.makedirs(path, exist_ok=True)
        engine.save(
            SaveLoadMeta(
                path=path,
                weight_format="hf",
                with_optim=self.for_recover,
                tokenizer=tokenizer,
            )
        )
        self.timer.reset()
        self.last_save_path = path
        # the pointer flips only AFTER the checkpoint fully landed, via
        # write-then-rename: readers (resume tooling, eval jobs) either see
        # the previous complete checkpoint's name or this one's, never a
        # name for a half-written directory
        atomic_write_text(
            os.path.join(self.save_root(), LATEST_POINTER),
            os.path.basename(path) + "\n",
        )
        self.gc(protect=protect)
        logger.info("saved checkpoint at %s", path)
        return path

    def latest_checkpoint(self) -> str | None:
        """Path named by the ``latest`` pointer, if present and valid."""
        pointer = os.path.join(self.save_root(), LATEST_POINTER)
        try:
            with open(pointer) as f:
                name = f.read().strip()
        except OSError:
            return None
        path = os.path.join(self.save_root(), name)
        return path if name and os.path.isdir(path) else None

    def resolve_latest_checkpoint(self, verify=None) -> str | None:
        """The ``latest`` pointer target, VALIDATED — the resume-time
        entry point. A pointer naming a GC'd directory or one that fails
        ``verify`` (default: digest verification for manifest checkpoints,
        existence+non-emptiness otherwise) does not crash the restore
        mid-flight: the scan falls back to the newest checkpoint directory
        that verifies, with a loud warning naming what was wrong with the
        pointer. Returns None when nothing on disk verifies."""
        if verify is None:
            from areal_tpu.utils.checkpoint import verify_checkpoint_dir

            verify = verify_checkpoint_dir
        root = self.save_root()
        pointed = self.latest_checkpoint()
        reason = "pointer missing or names a GC'd directory"
        if pointed is not None:
            ok, why = verify(pointed)
            if ok:
                return pointed
            reason = f"pointer names {pointed}: {why}"
        # newest-first fallback over every checkpoint-shaped directory
        try:
            names = os.listdir(root)
        except OSError:
            names = []
        entries = sorted(
            (
                (int(m.group(3)), name)
                for name in names
                if (m := _CKPT_DIR_RE.match(name))
                and os.path.isdir(os.path.join(root, name))
            ),
            reverse=True,
        )
        for _, name in entries:
            path = os.path.join(root, name)
            if path == pointed:
                continue  # already failed above
            ok, why = verify(path)
            if ok:
                logger.warning(
                    "latest checkpoint pointer is invalid (%s); falling "
                    "back to newest verifying checkpoint %s",
                    reason,
                    path,
                )
                return path
        if pointed is not None or entries:
            logger.warning(
                "no verifying checkpoint under %s (%s)", root, reason
            )
        return None

    def gc(self, protect: Iterable[str] = ()) -> list[str]:
        """Retention GC: keep the newest ``keep_last`` checkpoints, plus
        every checkpoint whose global_step is a multiple of ``keep_every``,
        plus anything in ``protect`` and the ``latest`` pointer target.
        No-op unless a retention knob is set. Returns the deleted paths."""
        keep_last = self.config.keep_last
        keep_every = self.config.keep_every
        if keep_last is None and keep_every is None:
            return []
        root = self.save_root()
        try:
            names = os.listdir(root)
        except OSError:
            return []
        entries = []
        for name in names:
            m = _CKPT_DIR_RE.match(name)
            if m and os.path.isdir(os.path.join(root, name)):
                entries.append((int(m.group(3)), name))
        entries.sort()
        protected = {os.path.basename(os.path.normpath(p)) for p in protect if p}
        latest = self.latest_checkpoint()
        if latest:
            protected.add(os.path.basename(latest))
        if self.last_save_path:
            protected.add(os.path.basename(self.last_save_path))
        keep: set[str] = set(protected)
        # the newest checkpoint always survives, even under keep_every-only
        n_newest = max(keep_last if keep_last is not None else 1, 1)
        keep.update(name for _, name in entries[-n_newest:])
        if keep_every is not None and keep_every > 0:
            keep.update(
                name for gs, name in entries if gs % keep_every == 0
            )
        deleted = []
        for _, name in entries:
            if name in keep:
                continue
            path = os.path.join(root, name)
            shutil.rmtree(path, ignore_errors=True)
            deleted.append(path)
        if deleted:
            logger.info(
                "retention GC deleted %d checkpoint(s) under %s "
                "(keep_last=%s keep_every=%s, %d protected)",
                len(deleted),
                root,
                keep_last,
                keep_every,
                len(protected),
            )
        return deleted

    def state_dict(self) -> dict:
        return {
            "timer": self.timer.state_dict(),
            "last_save_path": self.last_save_path,
        }

    def load_state_dict(self, s: dict):
        self.timer.load_state_dict(s.get("timer", {}))
        self.last_save_path = s.get("last_save_path", self.last_save_path)


class Evaluator:
    """Runs a user eval_fn on the saver-style frequency (reference
    areal/utils/evaluator.py)."""

    def __init__(self, config: EvaluatorConfig, ft_spec):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )

    def evaluate(
        self, eval_fn: Callable[[], None], step: StepInfo, force: bool = False
    ) -> bool:
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return False
        eval_fn()
        self.timer.reset()
        return True

    def state_dict(self) -> dict:
        return {"timer": self.timer.state_dict()}

    def load_state_dict(self, s: dict):
        self.timer.load_state_dict(s.get("timer", {}))
