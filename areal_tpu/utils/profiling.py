"""jax.profiler capture around training phases.

The TPU counterpart of the reference's torch-profiler kernel-time
attribution (realhf/base/monitor.py:404-610): instead of parsing CUDA
kernel categories out of chrome traces, capture a windowed
``jax.profiler.trace`` (viewable in TensorBoard / Perfetto, with XLA op
and fusion attribution built in) for a configured span of steps.

Analytic FLOPs/MFU counters live in utils/perf.py (monitor.py:288-403
equivalent) and are always on; trace capture is opt-in via ProfilerConfig.
"""

from __future__ import annotations

import contextlib
import os

from areal_tpu.utils import logging

logger = logging.getLogger("profiling")


class StepProfiler:
    """Capture a jax.profiler trace for steps [start_step, start_step+num_steps).

    Usage (train loop):
        with StepProfiler(cfg.profiler) as profiler:
            for step in ...:
                with profiler.step(step):
                    ...train...

    The context-manager form (or an explicit ``close()`` in the loop's
    ``finally`` and in the graceful-shutdown path) matters: if the loop
    exits — normal end of data, a crash, or a SIGTERM drain — before
    ``start_step + num_steps``, an in-flight ``jax.profiler`` capture
    would otherwise never see ``stop_trace()`` and the whole trace is
    lost. ``close()`` finalizes any active capture and is idempotent.
    """

    def __init__(self, config):
        self.config = config
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.config is not None and getattr(self.config, "enabled", False)

    @property
    def active(self) -> bool:
        """A jax.profiler capture is currently in flight — the signal the
        step timeline stamps onto its records so the profiled window is
        findable in the Perfetto join."""
        return self._active

    @contextlib.contextmanager
    def step(self, global_step: int):
        if not self.enabled:
            yield
            return
        import jax

        cfg = self.config
        start = cfg.start_step
        stop = cfg.start_step + cfg.num_steps
        if global_step == start and not self._active:
            os.makedirs(cfg.dir, exist_ok=True)
            jax.profiler.start_trace(cfg.dir)
            self._active = True
            logger.info("profiler trace started -> %s", cfg.dir)
        try:
            yield
        finally:
            if self._active and global_step + 1 >= stop:
                jax.profiler.stop_trace()
                self._active = False
                logger.info("profiler trace stopped (step %d)", global_step)

    def close(self):
        """Finalize an in-flight capture (idempotent). Called from the
        trainer's ``finally`` and the graceful-shutdown path so an early
        exit (drain, crash, short run) flushes the trace instead of
        losing it."""
        if self._active:
            self._active = False
            import jax

            try:
                jax.profiler.stop_trace()
                logger.info(
                    "profiler trace finalized early (close) -> %s",
                    self.config.dir,
                )
            except Exception:
                # a torn profiler session must not mask the original
                # exception unwinding through the trainer's finally
                logger.exception("profiler stop_trace failed in close()")

    def __enter__(self) -> "StepProfiler":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
