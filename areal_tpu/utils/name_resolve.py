"""Pluggable KV service-discovery ("name resolve").

Capability parity with the reference's ``areal/utils/name_resolve.py`` (memory /
NFS / etcd / ray repositories, add/get/wait/delete/subtree watch). The TPU
build keeps the same abstraction; backends here are:

- ``MemoryNameRecordRepository`` — in-process dict (unit tests, single proc).
- ``NfsNameRecordRepository`` — files on a shared filesystem (multi-host without
  extra services; works on any POSIX shared mount, e.g. GCS-fuse on TPU pods).
- ``EtcdNameRecordRepository`` — etcd v3 over its HTTP/JSON gateway (stdlib
  urllib only; the reference's Etcd3NameRecordRepository role for clusters
  with a real coordination service).

Keys are slash-separated paths; values are strings. ``add(..., delete_on_exit)``
records keys for atexit cleanup, matching the reference semantics.
"""

from __future__ import annotations

import atexit
import dataclasses
import os
import shutil
import threading
import time
import uuid
from abc import ABC, abstractmethod

from areal_tpu.utils import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class TimeoutError_(Exception):
    pass


class NameRecordRepository(ABC):
    @abstractmethod
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        replace: bool = False,
    ) -> None: ...

    @abstractmethod
    def get(self, name: str) -> str: ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> list[str]:
        """Return the key names (not values) under the subtree, sorted."""

    @abstractmethod
    def delete(self, name: str) -> None: ...

    @abstractmethod
    def clear_subtree(self, name_root: str) -> None: ...

    def add_subentry(self, name_root: str, value: str, **kwargs) -> str:
        sub = str(uuid.uuid4())[:8]
        name = f"{name_root}/{sub}"
        self.add(name, value, **kwargs)
        return name

    def wait(
        self, name: str, timeout: float | None = None, poll_frequency: float = 0.1
    ) -> str:
        start = time.monotonic()
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if timeout is not None and time.monotonic() - start > timeout:
                    raise TimeoutError_(f"Timeout waiting for key: {name}")
                time.sleep(poll_frequency)

    def reset(self) -> None:
        pass

    def close(self) -> None:
        pass


class MemoryNameRecordRepository(NameRecordRepository):
    """Process-local dict-backed repository (thread-safe)."""

    def __init__(self):
        self._store: dict[str, str] = {}
        self._lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def get_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return [
                v
                for k, v in sorted(self._store.items())
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]

    def find_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return sorted(
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            )

    def delete(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]

    def clear_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            for k in [
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]:
                del self._store[k]

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """Shared-filesystem repository: one file per key under ``record_root``.

    Works across hosts given any shared POSIX mount. Values are written
    atomically via rename.
    """

    def __init__(self, record_root: str = "/tmp/areal_tpu/name_resolve"):
        self.record_root = record_root
        self._to_delete: set[str] = set()
        os.makedirs(record_root, exist_ok=True)
        atexit.register(self._cleanup)

    def _path(self, name: str) -> str:
        return os.path.join(self.record_root, name.strip("/"), "ENTRY")

    def add(self, name, value, delete_on_exit=True, replace=False):
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if replace:
            tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
            with open(tmp, "w") as f:
                f.write(str(value))
            os.replace(tmp, path)
        else:
            # atomic exclusive create WITH atomic content visibility: write
            # the value to a private tmp file first, then hardlink it into
            # place (the classic NFS-safe technique). O_CREAT|O_EXCL + write
            # would expose an EMPTY entry between the two ops — a concurrent
            # wait()/get() read "" instead of the value (observed flake:
            # test_wait_concurrent[nfs]). link() both fails on an existing
            # entry (the DistributedLock acquire contract) and publishes the
            # fully-written file in one op.
            tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
            with open(tmp, "w") as f:
                f.write(str(value))
            try:
                os.link(tmp, path)
            except FileExistsError:
                # NFS retransmit caveat: the LINK RPC may succeed but its
                # reply get lost; the kernel retry then sees EEXIST for OUR
                # OWN entry. st_nlink == 2 on tmp proves the link landed.
                if os.stat(tmp).st_nlink == 2:
                    os.unlink(tmp)
                else:
                    os.unlink(tmp)
                    raise NameEntryExistsError(name) from None
            except OSError as e:
                import errno

                if e.errno not in (
                    errno.EPERM, errno.ENOTSUP, errno.EOPNOTSUPP,
                    errno.EXDEV, errno.ENOSYS,  # FUSE mounts return ENOSYS
                ):
                    # transient I/O (ESTALE/EIO/...) must propagate — the
                    # no-hardlink fallback would reintroduce the
                    # empty-entry race this path exists to fix
                    os.unlink(tmp)
                    raise
                # filesystem without hardlinks (gcsfuse/FUSE): fall back to
                # exclusive create + write — atomic existence, weaker
                # content visibility (a concurrent get may briefly see "")
                os.unlink(tmp)
                try:
                    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    raise NameEntryExistsError(name) from None
                with os.fdopen(fd, "w") as f:
                    f.write(str(value))
            else:
                os.unlink(tmp)
        if delete_on_exit:
            self._to_delete.add(name)

    def get(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        with open(path) as f:
            return f.read()

    def _iter_subtree(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if not os.path.isdir(root):
            return
        for dirpath, _, filenames in sorted(os.walk(root)):
            if "ENTRY" in filenames:
                rel = os.path.relpath(dirpath, self.record_root)
                yield rel.replace(os.sep, "/")

    def get_subtree(self, name_root):
        return [self.get(k) for k in self.find_subtree(name_root)]

    def find_subtree(self, name_root):
        return sorted(self._iter_subtree(name_root))

    def delete(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        self._to_delete.discard(name)

    def clear_subtree(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if os.path.isdir(root):
            shutil.rmtree(root, ignore_errors=True)

    def _cleanup(self):
        for name in list(self._to_delete):
            try:
                self.delete(name)
            # atexit teardown: logging handlers may already be closed,
            # and a half-gone backend is the expected case here
            except Exception:  # arealint: disable=swallowed-exception
                pass


class EtcdNameRecordRepository(NameRecordRepository):
    """etcd v3 via the HTTP/JSON grpc-gateway (/v3/kv/*): no client library
    needed in the image. Values and keys are base64 per the gateway wire
    format. Exclusive create uses an etcd txn on create_revision=0 — atomic
    cluster-wide, so DistributedLock works across hosts."""

    def __init__(self, endpoint: str):
        import base64 as _b64  # noqa: F401

        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.startswith("http"):
            self.endpoint = "http://" + self.endpoint
        self._to_delete: set[str] = set()
        atexit.register(self._cleanup)

    @staticmethod
    def _b64(s: str) -> str:
        import base64

        return base64.b64encode(s.encode()).decode()

    @staticmethod
    def _unb64(s: str) -> str:
        import base64

        return base64.b64decode(s.encode()).decode()

    def _call(self, path: str, payload: dict) -> dict:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/v3/kv/{path}",
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return _json.loads(resp.read())

    def add(self, name, value, delete_on_exit=True, replace=False):
        name = name.rstrip("/")
        if replace:
            self._call("put", {"key": self._b64(name), "value": self._b64(str(value))})
        else:
            # txn: put only if the key was never created (atomic)
            out = self._call(
                "txn",
                {
                    "compare": [
                        {
                            "key": self._b64(name),
                            "target": "CREATE",
                            "create_revision": "0",
                        }
                    ],
                    "success": [
                        {
                            "request_put": {
                                "key": self._b64(name),
                                "value": self._b64(str(value)),
                            }
                        }
                    ],
                },
            )
            if not out.get("succeeded", False):
                raise NameEntryExistsError(name)
        if delete_on_exit:
            self._to_delete.add(name)

    def get(self, name):
        name = name.rstrip("/")
        out = self._call("range", {"key": self._b64(name)})
        kvs = out.get("kvs") or []
        if not kvs:
            raise NameEntryNotFoundError(name)
        return self._unb64(kvs[0]["value"])

    def _range_prefix(self, prefix: str) -> list[tuple[str, str]]:
        start = prefix.rstrip("/") + "/"
        end = start[:-1] + chr(ord("/") + 1)
        out = self._call(
            "range", {"key": self._b64(start), "range_end": self._b64(end)}
        )
        return [
            (self._unb64(kv["key"]), self._unb64(kv["value"]))
            for kv in out.get("kvs") or []
        ]

    def get_subtree(self, name_root):
        vals = [v for _k, v in self._range_prefix(name_root)]
        try:
            vals.insert(0, self.get(name_root))
        except NameEntryNotFoundError:
            pass
        return vals

    def find_subtree(self, name_root):
        keys = [k for k, _v in self._range_prefix(name_root)]
        try:
            self.get(name_root)
            keys.insert(0, name_root.rstrip("/"))
        except NameEntryNotFoundError:
            pass
        return sorted(keys)

    def delete(self, name):
        name = name.rstrip("/")
        self._call("deleterange", {"key": self._b64(name)})
        self._to_delete.discard(name)

    def clear_subtree(self, name_root):
        start = name_root.rstrip("/")
        end = start + chr(ord("/") + 1)
        self._call(
            "deleterange",
            {"key": self._b64(start), "range_end": self._b64(end)},
        )

    def _cleanup(self):
        for name in list(self._to_delete):
            try:
                self.delete(name)
            # atexit teardown: logging handlers may already be closed,
            # and a half-gone backend is the expected case here
            except Exception:  # arealint: disable=swallowed-exception
                pass


@dataclasses.dataclass
class NameResolveConfig:
    """Mirrors the reference's NameResolveConfig (areal/api/cli_args.py:964)."""

    type: str = "nfs"  # "memory" | "nfs" | "etcd"
    nfs_record_root: str = "/tmp/areal_tpu/name_resolve"
    etcd_endpoint: str = "127.0.0.1:2379"


DEFAULT_REPOSITORY: NameRecordRepository = MemoryNameRecordRepository()


def reconfigure(config: NameResolveConfig) -> NameRecordRepository:
    global DEFAULT_REPOSITORY
    if config.type == "memory":
        DEFAULT_REPOSITORY = MemoryNameRecordRepository()
    elif config.type == "nfs":
        DEFAULT_REPOSITORY = NfsNameRecordRepository(config.nfs_record_root)
    elif config.type == "etcd":
        DEFAULT_REPOSITORY = EtcdNameRecordRepository(config.etcd_endpoint)
    else:
        raise ValueError(f"Unknown name_resolve type: {config.type}")
    return DEFAULT_REPOSITORY


def add(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add(name, value, **kwargs)


def add_subentry(name_root, value, **kwargs):
    return DEFAULT_REPOSITORY.add_subentry(name_root, value, **kwargs)


def get(name):
    return DEFAULT_REPOSITORY.get(name)


def get_subtree(name_root):
    return DEFAULT_REPOSITORY.get_subtree(name_root)


def find_subtree(name_root):
    return DEFAULT_REPOSITORY.find_subtree(name_root)


def wait(name, timeout=None, poll_frequency=0.1):
    return DEFAULT_REPOSITORY.wait(name, timeout, poll_frequency)


def delete(name):
    return DEFAULT_REPOSITORY.delete(name)


def clear_subtree(name_root):
    return DEFAULT_REPOSITORY.clear_subtree(name_root)
