"""Pluggable KV service-discovery ("name resolve").

Capability parity with the reference's ``areal/utils/name_resolve.py`` (memory /
NFS / etcd / ray repositories, add/get/wait/delete/subtree watch). The TPU
build keeps the same abstraction; backends here are:

- ``MemoryNameRecordRepository`` — in-process dict (unit tests, single proc).
- ``NfsNameRecordRepository`` — files on a shared filesystem (multi-host without
  extra services; works on any POSIX shared mount, e.g. GCS-fuse on TPU pods).

Keys are slash-separated paths; values are strings. ``add(..., delete_on_exit)``
records keys for atexit cleanup, matching the reference semantics.
"""

from __future__ import annotations

import atexit
import dataclasses
import os
import shutil
import threading
import time
import uuid
from abc import ABC, abstractmethod

from areal_tpu.utils import logging

logger = logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class TimeoutError_(Exception):
    pass


class NameRecordRepository(ABC):
    @abstractmethod
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        replace: bool = False,
    ) -> None: ...

    @abstractmethod
    def get(self, name: str) -> str: ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> list[str]: ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> list[str]:
        """Return the key names (not values) under the subtree, sorted."""

    @abstractmethod
    def delete(self, name: str) -> None: ...

    @abstractmethod
    def clear_subtree(self, name_root: str) -> None: ...

    def add_subentry(self, name_root: str, value: str, **kwargs) -> str:
        sub = str(uuid.uuid4())[:8]
        name = f"{name_root}/{sub}"
        self.add(name, value, **kwargs)
        return name

    def wait(
        self, name: str, timeout: float | None = None, poll_frequency: float = 0.1
    ) -> str:
        start = time.monotonic()
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if timeout is not None and time.monotonic() - start > timeout:
                    raise TimeoutError_(f"Timeout waiting for key: {name}")
                time.sleep(poll_frequency)

    def reset(self) -> None:
        pass

    def close(self) -> None:
        pass


class MemoryNameRecordRepository(NameRecordRepository):
    """Process-local dict-backed repository (thread-safe)."""

    def __init__(self):
        self._store: dict[str, str] = {}
        self._lock = threading.Lock()

    def add(self, name, value, delete_on_exit=True, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def get_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return [
                v
                for k, v in sorted(self._store.items())
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]

    def find_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            return sorted(
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            )

    def delete(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]

    def clear_subtree(self, name_root):
        with self._lock:
            prefix = name_root.rstrip("/") + "/"
            for k in [
                k
                for k in self._store
                if k.startswith(prefix) or k == name_root.rstrip("/")
            ]:
                del self._store[k]

    def reset(self):
        with self._lock:
            self._store.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """Shared-filesystem repository: one file per key under ``record_root``.

    Works across hosts given any shared POSIX mount. Values are written
    atomically via rename.
    """

    def __init__(self, record_root: str = "/tmp/areal_tpu/name_resolve"):
        self.record_root = record_root
        self._to_delete: set[str] = set()
        os.makedirs(record_root, exist_ok=True)
        atexit.register(self._cleanup)

    def _path(self, name: str) -> str:
        return os.path.join(self.record_root, name.strip("/"), "ENTRY")

    def add(self, name, value, delete_on_exit=True, replace=False):
        path = self._path(name)
        if os.path.exists(path) and not replace:
            raise NameEntryExistsError(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)
        if delete_on_exit:
            self._to_delete.add(name)

    def get(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        with open(path) as f:
            return f.read()

    def _iter_subtree(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if not os.path.isdir(root):
            return
        for dirpath, _, filenames in sorted(os.walk(root)):
            if "ENTRY" in filenames:
                rel = os.path.relpath(dirpath, self.record_root)
                yield rel.replace(os.sep, "/")

    def get_subtree(self, name_root):
        return [self.get(k) for k in self.find_subtree(name_root)]

    def find_subtree(self, name_root):
        return sorted(self._iter_subtree(name_root))

    def delete(self, name):
        path = self._path(name)
        if not os.path.exists(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        self._to_delete.discard(name)

    def clear_subtree(self, name_root):
        root = os.path.join(self.record_root, name_root.strip("/"))
        if os.path.isdir(root):
            shutil.rmtree(root, ignore_errors=True)

    def _cleanup(self):
        for name in list(self._to_delete):
            try:
                self.delete(name)
            except Exception:
                pass


@dataclasses.dataclass
class NameResolveConfig:
    """Mirrors the reference's NameResolveConfig (areal/api/cli_args.py:964)."""

    type: str = "nfs"  # "memory" | "nfs"
    nfs_record_root: str = "/tmp/areal_tpu/name_resolve"


DEFAULT_REPOSITORY: NameRecordRepository = MemoryNameRecordRepository()


def reconfigure(config: NameResolveConfig) -> NameRecordRepository:
    global DEFAULT_REPOSITORY
    if config.type == "memory":
        DEFAULT_REPOSITORY = MemoryNameRecordRepository()
    elif config.type == "nfs":
        DEFAULT_REPOSITORY = NfsNameRecordRepository(config.nfs_record_root)
    else:
        raise ValueError(f"Unknown name_resolve type: {config.type}")
    return DEFAULT_REPOSITORY


def add(name, value, **kwargs):
    return DEFAULT_REPOSITORY.add(name, value, **kwargs)


def add_subentry(name_root, value, **kwargs):
    return DEFAULT_REPOSITORY.add_subentry(name_root, value, **kwargs)


def get(name):
    return DEFAULT_REPOSITORY.get(name)


def get_subtree(name_root):
    return DEFAULT_REPOSITORY.get_subtree(name_root)


def find_subtree(name_root):
    return DEFAULT_REPOSITORY.find_subtree(name_root)


def wait(name, timeout=None, poll_frequency=0.1):
    return DEFAULT_REPOSITORY.wait(name, timeout, poll_frequency)


def delete(name):
    return DEFAULT_REPOSITORY.delete(name)


def clear_subtree(name_root):
    return DEFAULT_REPOSITORY.clear_subtree(name_root)
