"""Loss / logprob / advantage math (JAX).

Capability parity with the reference's ``areal/utils/functional.py``
(gather_logprobs:43, gather_logprobs_entropy:84, masked_normalization:131,
ppo_actor_loss_fn:171 — the decoupled-PPO objective, ppo_critic_loss_fn:247,
dynamic_sampling:314, reward_overlong_penalty:376) and its cuGAE CUDA kernels
(csrc/cugae/gae.cu). TPU-native design notes:

- log-softmax gathers are plain fused XLA ops over the full [T, V] logits —
  no manual chunking needed; XLA tiles the reduction onto the VPU/MXU.
- GAE is a time-reversed ``jax.lax.scan`` over the padded [B, T] batch —
  the sequential dependence is inherent (it's a linear recurrence), and a
  scan over T with B lanes vectorized is the TPU-shaped formulation of the
  reference's one-CUDA-thread-per-sequence kernel.
- Everything is pure and jittable; host-side helpers (dynamic_sampling)
  operate on numpy and stay out of jit.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

TensorDict = dict[str, Any]


# ---------------------------------------------------------------------------
# Logprob gathering
# ---------------------------------------------------------------------------


def gather_logprobs(
    logits: jnp.ndarray,  # [T, V] fp32
    labels: jnp.ndarray,  # [T] int32
    temperature: float = 1.0,
) -> jnp.ndarray:
    """Log-probability of ``labels`` under ``logits`` (reference :43)."""
    if temperature != 1.0:
        logits = logits / temperature
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return picked - logz


def gather_logprobs_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    temperature: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(logprobs, entropy) in one pass (reference :84)."""
    if temperature != 1.0:
        logits = logits / temperature
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    logp_full = logits - logz[:, None]
    entropy = -jnp.sum(jnp.exp(logp_full) * logp_full, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return picked - logz, entropy


# ---------------------------------------------------------------------------
# Masked normalization
# ---------------------------------------------------------------------------


def masked_normalization(
    x: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    dim=None,
    unbiased: bool = False,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Whiten ``x`` over ``dim`` counting only masked entries (reference :131).

    The reference all-reduces sums across DP ranks; under the JAX
    single-controller model the arrays are global, so plain reductions give
    the identical result.
    """
    xf = x.astype(jnp.float32)
    if dim is None:
        dim = tuple(range(x.ndim))
    if mask is None:
        factor = np.prod([x.shape[d] for d in dim]).astype(np.float32)
    else:
        m = mask.astype(jnp.float32)
        xf = xf * m
        factor = jnp.sum(m, axis=dim, keepdims=True)
    x_sum = jnp.sum(xf, axis=dim, keepdims=True)
    x_sq = jnp.sum(jnp.square(xf), axis=dim, keepdims=True)
    mean = x_sum / factor
    var = x_sq / factor - jnp.square(mean)
    if unbiased:
        var = var * factor / (factor - 1)
    return (xf - mean) / (jnp.sqrt(var) + eps)


# ---------------------------------------------------------------------------
# PPO losses
# ---------------------------------------------------------------------------


def ppo_actor_loss_fn(
    logprobs: jnp.ndarray,
    proximal_logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    eps_clip: float,
    loss_mask: jnp.ndarray,
    eps_clip_higher: float | None = None,
    c_clip: float | None = None,
    behav_imp_weight_cap: float | None = None,
) -> tuple[jnp.ndarray, dict]:
    """Decoupled-PPO policy loss (reference functional.py:171-235).

    ratio = exp(logp - proximal_logp) is clipped per PPO; the whole objective
    is reweighted by the behavior importance weight exp(proximal - behavioral),
    which corrects for rollout staleness (the AReaL decoupled objective).
    Returns (scalar mean-over-mask loss, stats dict of per-token arrays).
    """
    mask = loss_mask.astype(bool)
    count = jnp.maximum(jnp.sum(mask), 1)
    ratio = jnp.where(mask, jnp.exp(logprobs - proximal_logprobs), 0.0)
    hi = eps_clip if eps_clip_higher is None else eps_clip_higher
    clipped_ratio = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + hi)
    pg1 = -advantages * ratio
    pg2 = -advantages * clipped_ratio
    clip_mask = pg1 < pg2
    pg = jnp.maximum(pg1, pg2)
    if c_clip is not None:
        assert c_clip > 1.0, c_clip
        pg3 = jnp.sign(advantages) * c_clip * advantages
        dual_clip_mask = pg3 < pg
        pg = jnp.minimum(pg, pg3)
    else:
        dual_clip_mask = jnp.zeros_like(clip_mask)
    behav_kl = proximal_logprobs - old_logprobs
    behav_imp_weight = jnp.exp(behav_kl)
    if behav_imp_weight_cap is not None:
        behav_mask = (behav_imp_weight <= behav_imp_weight_cap) & mask
    else:
        behav_mask = mask
    behav_kl = jnp.where(behav_mask, behav_kl, 0.0)
    behav_imp_weight = jnp.where(behav_mask, behav_imp_weight, 0.0)
    pg = pg * behav_imp_weight
    logging_loss = pg
    loss = jnp.sum(jnp.where(mask, pg, 0.0)) / count
    stats = dict(
        loss=logging_loss,
        importance_weight=ratio,
        approx_kl=jax.lax.stop_gradient(logprobs - proximal_logprobs),
        clip_mask=clip_mask & mask,
        dual_clip_mask=dual_clip_mask & mask,
        behave_imp_weight=behav_imp_weight,
        behave_approx_kl=behav_kl,
        behave_mask=behav_mask,
    )
    return loss, stats


def ppo_loss_stats_host(
    logprobs: np.ndarray,
    proximal_logprobs: np.ndarray,
    old_logprobs: np.ndarray,
    advantages: np.ndarray,
    loss_mask: np.ndarray,
    eps_clip: float,
    eps_clip_higher: float | None = None,
    c_clip: float | None = None,
    behav_imp_weight_cap: float | None = None,
) -> dict[str, np.ndarray]:
    """Host-side (numpy) mirror of :func:`ppo_actor_loss_fn`'s per-token
    stats dict — the quantities the decoupled objective computes inside
    jit and discards. The RL-health observatory (utils/rl_health.py) calls
    this once per update batch; it must stay an exact transcription of the
    jitted math (pinned against it by tests/test_functional.py), so a
    reported clip fraction is the clip fraction the loss actually saw.

    Same conventions as the loss: ``ratio = exp(logprobs - proximal)``
    with masked tokens zeroed, ``clip_mask`` from the pessimistic-branch
    comparison (advantage sign matters — only binding clips count),
    ``behav_imp_weight = exp(proximal - old)`` with the cap mask applied.
    """
    mask = np.asarray(loss_mask).astype(bool)
    lp = np.asarray(logprobs, np.float32)
    prox = np.asarray(proximal_logprobs, np.float32)
    old = np.asarray(old_logprobs, np.float32)
    adv = np.asarray(advantages, np.float32)
    ratio = np.where(mask, np.exp(lp - prox), 0.0)
    hi = eps_clip if eps_clip_higher is None else eps_clip_higher
    clipped_ratio = np.clip(ratio, 1.0 - eps_clip, 1.0 + hi)
    pg1 = -adv * ratio
    pg2 = -adv * clipped_ratio
    clip_mask = (pg1 < pg2) & mask
    pg = np.maximum(pg1, pg2)
    if c_clip is not None:
        assert c_clip > 1.0, c_clip
        pg3 = np.sign(adv) * c_clip * adv
        dual_clip_mask = (pg3 < pg) & mask
    else:
        dual_clip_mask = np.zeros_like(clip_mask)
    behav_kl = prox - old
    behav_imp_weight = np.exp(behav_kl)
    if behav_imp_weight_cap is not None:
        behav_mask = (behav_imp_weight <= behav_imp_weight_cap) & mask
    else:
        behav_mask = mask
    behav_kl = np.where(behav_mask, behav_kl, 0.0)
    behav_imp_weight = np.where(behav_mask, behav_imp_weight, 0.0)
    return dict(
        importance_weight=ratio,
        approx_kl=lp - prox,  # unmasked, like the loss's stop_gradient stat
        clip_mask=clip_mask,
        dual_clip_mask=dual_clip_mask,
        behave_imp_weight=behav_imp_weight,
        behave_approx_kl=behav_kl,
        behave_mask=behav_mask,
    )


def ppo_critic_loss_fn(
    value: jnp.ndarray,
    old_value: jnp.ndarray,
    target_value: jnp.ndarray,
    value_eps_clip: float,
    loss_mask: jnp.ndarray | None = None,
    loss_fn_type: str = "mse",
    huber_delta: float = 10.0,
) -> tuple[jnp.ndarray, dict]:
    """Clipped value loss (reference functional.py:247-312)."""

    def base(x, y):
        if loss_fn_type == "huber":
            diff = jnp.abs(x - y)
            return jnp.where(
                diff < huber_delta,
                0.5 * diff**2,
                huber_delta * (diff - 0.5 * huber_delta),
            )
        return 0.5 * (x - y) ** 2

    value_clipped = old_value + jnp.clip(
        value - old_value, -value_eps_clip, value_eps_clip
    )
    l_orig = base(value, target_value)
    l_clip = base(value_clipped, target_value)
    clip_mask = l_clip > l_orig
    value_loss = jnp.maximum(l_orig, l_clip)
    if loss_mask is not None:
        m = loss_mask.astype(bool)
        loss = jnp.sum(jnp.where(m, value_loss, 0.0)) / jnp.maximum(jnp.sum(m), 1)
        clip_mask = clip_mask & m
    else:
        loss = jnp.mean(value_loss)
    return loss, dict(loss=value_loss, clip_mask=clip_mask)


# ---------------------------------------------------------------------------
# GAE — the cuGAE equivalent as a lax.scan linear recurrence
# ---------------------------------------------------------------------------


def gae_padded(
    rewards: jnp.ndarray,  # [B, T] fp32
    values: jnp.ndarray,  # [B, T] fp32
    loss_mask: jnp.ndarray,  # [B, T] (already shifted like the reference)
    seq_no_eos_mask: jnp.ndarray,  # [B] bool — sequence hit max length
    discount: float,
    gae_lambda: float,
) -> jnp.ndarray:
    """Masked GAE over a padded batch, exactly mirroring the reference's
    backward loop (areal/engine/ppo/actor.py:136-151): tokens with mask 0
    pass ``nextvalues``/``lastgaelam`` through unchanged; the bootstrap value
    at T-1 is ``values[:, T-1]`` only when the sequence never emitted EOS.

    Formulated as a reverse-time ``lax.scan`` with B vectorized lanes — the
    TPU analogue of cuGAE's one-thread-per-sequence kernel
    (csrc/cugae/gae.cu:10-28).
    """
    b, t = rewards.shape
    mask = loss_mask.astype(jnp.float32)
    init = (
        values[:, t - 1] * seq_no_eos_mask.astype(jnp.float32),  # nextvalues
        jnp.zeros((b,), jnp.float32),  # lastgaelam
    )

    def step(carry, xs):
        nextvalues, lastgaelam = carry
        r_t, v_t, m_t = xs
        delta = r_t + discount * nextvalues - v_t
        newgaelam = delta + discount * gae_lambda * lastgaelam
        nextvalues = nextvalues * (1 - m_t) + v_t * m_t
        lastgaelam = lastgaelam * (1 - m_t) + newgaelam * m_t
        return (nextvalues, lastgaelam), lastgaelam

    xs = (rewards[:, : t - 1].T, values[:, : t - 1].T, mask[:, : t - 1].T)
    _, adv_rev = jax.lax.scan(step, init, xs, reverse=True)
    # adv_rev[t] is lastgaelam produced at time t (already in forward order
    # thanks to reverse=True); the reference appends a zero column at T-1.
    advantages = jnp.concatenate(
        [adv_rev.T, jnp.zeros((b, 1), jnp.float32)], axis=1
    )
    return advantages


def gae_packed(
    rewards: jnp.ndarray,  # [total] fp32, packed
    values: jnp.ndarray,  # [total] fp32
    segment_ids: jnp.ndarray,  # [total] int32, pad = -1
    bootstrap: jnp.ndarray,  # [total] fp32 — nextvalue at each seq's last token
    discount: float,
    gae_lambda: float,
) -> jnp.ndarray:
    """GAE over a packed 1D stream (cuGAE gae_1d_nolp_misalign equivalent,
    csrc/cugae/gae.cu:10-28). A single reverse scan; the recurrence resets at
    segment boundaries detected from ``segment_ids``."""
    # last-token flag: next token belongs to a different segment
    next_seg = jnp.concatenate([segment_ids[1:], jnp.full((1,), -2, jnp.int32)])
    is_last = segment_ids != next_seg

    def step(carry, xs):
        r, v, boot, last = xs
        # carry holds (A_{t+1}, V_{t+1}); at a segment's last token the
        # recurrence restarts from (0, bootstrap).
        gaelam_in = jnp.where(last, 0.0, carry[0])
        nextv_in = jnp.where(last, boot, carry[1])
        delta = r + discount * nextv_in - v
        gaelam = delta + discount * gae_lambda * gaelam_in
        return (gaelam, v), gaelam

    (_, _), adv = jax.lax.scan(
        step,
        (jnp.float32(0.0), jnp.float32(0.0)),
        (rewards, values, bootstrap, is_last),
        reverse=True,
    )
    return jnp.where(segment_ids >= 0, adv, 0.0)


# ---------------------------------------------------------------------------
# Host-side batch filters (numpy; out of jit by design)
# ---------------------------------------------------------------------------


def dynamic_sampling(
    data: TensorDict, group_size: int
) -> tuple[TensorDict, dict[str, int]]:
    """Drop whole groups whose rewards are all equal — DAPO-style filtering
    (reference functional.py:314-374). Assumes group members are adjacent."""
    rewards = np.asarray(data["rewards"])
    bs = rewards.shape[0]
    if group_size <= 0:
        return data, dict(n_group_kept=0, n_group_filtered=0)
    if bs % group_size != 0:
        return data, dict(n_group_kept=bs // max(group_size, 1), n_group_filtered=0)
    n_groups = bs // group_size
    grouped = rewards.reshape(n_groups, group_size)
    valid = ~np.all(grouped == grouped[:, :1], axis=1)
    mask = np.repeat(valid, group_size)
    if not mask.any():
        return data, dict(n_group_kept=0, n_group_filtered=n_groups)
    kept = int(valid.sum())
    out: TensorDict = {}
    for k, v in data.items():
        arr = np.asarray(v) if not np.isscalar(v) else v
        if hasattr(arr, "shape") and arr.ndim >= 1 and arr.shape[0] == bs:
            out[k] = arr[mask]
        else:
            out[k] = v
    return out, dict(n_group_kept=kept, n_group_filtered=n_groups - kept)


def reward_overlong_penalty(
    data: TensorDict,
    overlong_tokens: int,
    overlong_penalty_factor: float,
    max_response_length: int,
) -> TensorDict:
    """Linear penalty once the response exceeds max_len - overlong_tokens
    (reference functional.py:376-398, DAPO)."""
    rewards = np.asarray(data["rewards"], dtype=np.float32).copy()
    response_lengths = np.asarray(data["loss_mask"]).sum(axis=-1).astype(np.int64)
    expected = max_response_length - overlong_tokens
    exceed = response_lengths - expected
    penalty = np.minimum(-exceed / overlong_tokens * overlong_penalty_factor, 0.0)
    data["rewards"] = rewards + penalty.astype(np.float32)
    return data
