"""Analytic FLOPs / MFU accounting (TPU observability).

The TPU counterpart of the reference's analytic FLOPs counters for
llama-family train/generate (realhf/base/monitor.py:288-403) and its
per-MFC flops tracker (realhf/system/flops_counter.py): everything is
derived from the TransformerConfig, so the engines can report
model-flops-utilization per step without profiling overhead.

Conventions:
- matmul params counted once; 2 FLOPs per MAC; backward = 2x forward
  (so train = 6 * params * tokens for the matmul core).
- attention scores/values add 4 * ctx * nh * d per token forward
  (ctx = average causal context = seqlen / 2 for full sequences); flash
  recomputation in the backward adds roughly one extra forward, folded
  into the 3x factor conservatively.
- MoE counts only the activated experts (top-k), matching how the
  reference's counter treats activated parameters.
"""

from __future__ import annotations

import jax

# Dense bf16 peak FLOP/s per chip by device_kind prefix. Sources: public TPU
# spec sheets (v4 275, v5e 197, v5p 459, v6e 918 TFLOP/s).
_PEAK_BF16 = [
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v5", 459e12),
    ("TPU v4 lite", 138e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
]


def device_kind(device=None) -> str:
    """The backend's device kind string ("TPU v5e", "cpu", ...) — the
    label value that keeps CPU-rehearsal MFU series distinct from (and
    absent next to) real-chip ones."""
    if device is None:
        devices = jax.devices()
        if not devices:
            return "unknown"
        device = devices[0]
    return getattr(device, "device_kind", "") or "unknown"


def chip_peak_flops(device=None) -> float | None:
    """Peak dense bf16 FLOP/s of one chip, or None when unknown (CPU)."""
    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in _PEAK_BF16:
        if kind.startswith(prefix):
            return peak
    return None


def matmul_params(cfg) -> int:
    """Parameters that participate in matmuls (per-token active set)."""
    h = cfg.hidden_size
    per_layer = h * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * h  # qkv + o
    if cfg.is_moe:
        per_layer += h * cfg.num_experts  # router
        per_layer += 3 * h * cfg.moe_intermediate_size * cfg.num_experts_per_tok
    else:
        n_mlp_mats = 3 if getattr(cfg, "mlp_gated", True) else 2  # gpt2: fc+proj
        per_layer += n_mlp_mats * h * cfg.intermediate_size
    total = cfg.num_hidden_layers * per_layer
    # lm_head (or the tied-embedding matmul — the FLOPs are real either way);
    # critics project to 1, negligible
    if not cfg.is_critic:
        total += h * cfg.vocab_size
    return total


def attn_flops_per_token_fwd(cfg, avg_ctx: float) -> float:
    """scores (QK^T) + values (PV): 4 * ctx * nh * d MACs -> FLOPs."""
    return 4.0 * avg_ctx * cfg.num_attention_heads * cfg.head_dim


def train_flops_per_token(cfg, avg_seqlen: float) -> float:
    """Forward+backward FLOPs per trained token (6N + attention term)."""
    mm = 6.0 * matmul_params(cfg)
    attn = 3.0 * cfg.num_hidden_layers * attn_flops_per_token_fwd(
        cfg, avg_seqlen / 2.0
    )
    return mm + attn


def decode_flops_per_token(cfg, avg_ctx: float) -> float:
    """Forward-only FLOPs per generated token at a given KV context."""
    return 2.0 * matmul_params(cfg) + cfg.num_hidden_layers * (
        attn_flops_per_token_fwd(cfg, avg_ctx)
    )


def mfu(tokens_per_sec: float, flops_per_token: float, n_chips: int = 1,
        peak: float | None = None) -> float | None:
    """Model FLOPs utilization in [0, 1], or None off-TPU."""
    peak = peak if peak is not None else chip_peak_flops()
    if peak is None or tokens_per_sec <= 0:
        return None
    return tokens_per_sec * flops_per_token / (peak * n_chips)
