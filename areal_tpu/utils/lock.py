"""Distributed lock over the name-resolve KV.

The reference builds DistributedLock on the torch c10d TCPStore with an
atomic add-counter + owner-token validation + exponential backoff
(areal/utils/lock.py:9-60, exercised by tests/torchrun/run_lock.py). Here
the same contract rides the name-resolve repository's atomic
exclusive-create ``add(replace=False)`` (dict setdefault for memory, O_EXCL
file create for NFS): whoever creates the key owns the lock; release
validates the owner token before deleting; a TTL lets a crashed owner's
lock be broken.
"""

from __future__ import annotations

import time
import uuid

from areal_tpu.utils import logging, name_resolve

logger = logging.getLogger("DistributedLock")


class DistributedLock:
    def __init__(
        self,
        name: str,
        ttl: float = 120.0,
        poll_interval: float = 0.05,
        max_poll_interval: float = 1.0,
    ):
        self.key = f"locks/{name.strip('/')}"
        self.ttl = ttl
        self.poll_interval = poll_interval
        self.max_poll_interval = max_poll_interval
        self.token = uuid.uuid4().hex
        self._held = False

    def _try_acquire(self) -> bool:
        try:
            name_resolve.add(
                self.key, f"{self.token}:{time.time()}", replace=False
            )
            return True
        except name_resolve.NameEntryExistsError:
            return False

    def _break_if_expired(self):
        try:
            value = name_resolve.get(self.key)
            _tok, ts = value.rsplit(":", 1)
            if time.time() - float(ts) > self.ttl:
                logger.warning("breaking expired lock %s", self.key)
                name_resolve.delete(self.key)
        except Exception:
            # raced with the owner's release — fine
            logger.debug("expired-lock break raced", exc_info=True)

    def acquire(self, timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        interval = self.poll_interval
        while True:
            if self._try_acquire():
                self._held = True
                return True
            self._break_if_expired()
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(interval)
            interval = min(interval * 2, self.max_poll_interval)  # backoff

    def release(self):
        if not self._held:
            return
        try:
            value = name_resolve.get(self.key)
            if value.rsplit(":", 1)[0] == self.token:  # owner validation
                name_resolve.delete(self.key)
            else:
                logger.warning(
                    "lock %s no longer owned by this holder", self.key
                )
        except Exception:
            logger.debug("lock release for %s raced", self.key, exc_info=True)
        self._held = False

    def __enter__(self):
        if not self.acquire():
            raise TimeoutError(f"could not acquire {self.key}")
        return self

    def __exit__(self, *exc):
        self.release()
