"""Peer-to-peer weight-propagation topology shared by the trainer client
and the inference servers.

The PR 5 weight sync streams one full copy of the model from the trainer
to EVERY server (``_stream_chunks_pipelined`` is per-server), so trainer
NIC egress scales O(N * model_size) per commit — the scaling ceiling once
the PR 12 autoscaler grows the fleet under load. This module holds the
topology half of the fix: the trainer pushes each chunk stream to a small
set of ROOT servers (``weight_propagation_fanout``), and each server
relays staged chunks to its children over ``POST /relay_weights``
(inference/server.py). Trainer egress drops to fanout x model bytes and
commit latency goes O(log N) in the fleet size.

Wire format of a subtree (the ``x-areal-relay-subtree`` header): a JSON
list of nodes ``{"addr": "host:port", "children": [...]}`` — each relay
hop stages the chunk locally (the verbatim PR 5
``stage_weight_chunk``/``commit_staged_weights`` path, so per-version
tags, the HTTP 412 delta-base guard, and torn-stream supersede all apply
PER HOP) and forwards the raw body to each child with the child's own
``children`` as the next header.

Authentication: the relay hop and the peer-push endpoint trigger
outbound pushes and weight overwrites, so they carry a shared-secret
token (``x-areal-relay-token``). The server reads its expected token
from ``AREAL_RELAY_TOKEN`` (set by the launcher) or accepts everything
when unset; comparison is constant-time.
"""

from __future__ import annotations

import hmac
import os

#: header carrying the JSON subtree a relay hop is responsible for
RELAY_SUBTREE_HEADER = "x-areal-relay-subtree"
#: shared-secret header authenticating /relay_weights and
#: /push_weights_to_peer
RELAY_TOKEN_HEADER = "x-areal-relay-token"
#: server-side source of the expected token (launcher-exported)
RELAY_TOKEN_ENV = "AREAL_RELAY_TOKEN"


def build_tree(targets: list[str], fanout: int) -> dict[str, list[dict]]:
    """Balanced d-ary propagation forest over ``targets``: the first
    ``fanout`` addresses are roots (direct trainer push); every later
    address hangs under the earliest node with spare child capacity
    (breadth-first), so depth is O(log_fanout N) and every node forwards
    to at most ``fanout`` children. Deterministic in the input order —
    the caller passes the fenced target list, so every chunk of one
    update sees the same tree."""
    fanout = max(1, int(fanout))
    roots: dict[str, list[dict]] = {}
    bfs: list[dict] = []  # nodes in attach order, each with a children list
    for addr in targets:
        node = {"addr": addr, "children": []}
        if len(roots) < fanout:
            roots[addr] = node["children"]
            bfs.append(node)
            continue
        # earliest node with spare capacity: BFS order keeps the forest
        # balanced (depth grows only when a whole level is full)
        for parent in bfs:
            if len(parent["children"]) < fanout:
                parent["children"].append(node)
                break
        bfs.append(node)
    return roots


def flatten(nodes: list[dict]) -> list[str]:
    """Every address in a subtree, preorder (iterative: relay trees are
    shallow, but a hostile header must not recurse past the limit)."""
    out: list[str] = []
    stack = list(reversed(nodes))
    while stack:
        node = stack.pop()
        out.append(node["addr"])
        stack.extend(reversed(node.get("children") or []))
    return out


def prune(nodes: list[dict], addr: str) -> list[dict]:
    """Remove the node for ``addr`` (and its whole subtree) from a
    children list, in place at every level. Returns ``nodes`` for
    chaining. Descendants of a failed node are reported individually by
    the relay response, so pruning the subtree wholesale never drops an
    address silently — every member either stays in the tree or was
    already handed to the direct-push fallback."""
    stack = [nodes]
    while stack:
        children = stack.pop()
        for i, node in enumerate(children):
            if node["addr"] == addr:
                del children[i]
                break
            stack.append(node.get("children") or [])
    return nodes


def depth(roots: dict[str, list[dict]]) -> int:
    """Hop count of the deepest path (1 = trainer -> root only)."""
    best = 1 if roots else 0

    def walk(nodes: list[dict], d: int) -> None:
        nonlocal best
        for node in nodes:
            best = max(best, d)
            walk(node.get("children") or [], d + 1)

    for children in roots.values():
        walk(children, 2)
    return best


def validate_subtree(nodes) -> list[dict]:
    """Parse-time validation of a relay header: a list of
    ``{"addr": str, "children": [...]}`` nodes. Raises ``ValueError`` on
    anything else — a malformed header must 400, not 500-and-retry."""
    if not isinstance(nodes, list):
        raise ValueError("relay subtree must be a JSON list")
    out = []
    for node in nodes:
        if not isinstance(node, dict) or not isinstance(
            node.get("addr"), str
        ):
            raise ValueError("relay subtree nodes need a string 'addr'")
        out.append(
            {
                "addr": node["addr"],
                "children": validate_subtree(node.get("children") or []),
            }
        )
    return out


def expected_token() -> str:
    """The server's expected relay token ('' = authentication off)."""
    return os.environ.get(RELAY_TOKEN_ENV, "")


def token_ok(presented: str | None, expected: str | None = None) -> bool:
    """Constant-time token check. An empty expected token disables
    authentication (single-tenant dev runs); a configured one rejects
    missing or mismatched headers."""
    expected = expected_token() if expected is None else expected
    if not expected:
        return True
    return hmac.compare_digest(presented or "", expected)
