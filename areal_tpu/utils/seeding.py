"""Deterministic seeding across python/numpy/jax.

Parity: reference ``areal/utils/seeding.py`` (seeds torch/np/random per rank).
TPU-native version derives a `jax.random.PRNGKey` tree instead of torch seeds.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_BASE_SEED: int | None = None


def _mix(seed: int, key: str) -> int:
    digest = hashlib.sha256(f"{seed}-{key}".encode()).digest()
    return int.from_bytes(digest[:8], "little") % (2**31 - 1)


def set_random_seed(seed: int, key: str = "") -> None:
    """Seed python & numpy RNGs with a (seed, key)-derived value."""
    global _BASE_SEED
    _BASE_SEED = seed
    mixed = _mix(seed, key)
    random.seed(mixed)
    np.random.seed(mixed % (2**32 - 1))


def base_seed() -> int:
    return _BASE_SEED if _BASE_SEED is not None else 0


def prng_key(key: str = "", seed: int | None = None):
    """Derive a named jax PRNGKey; import jax lazily to keep utils CPU-cheap."""
    import jax

    s = seed if seed is not None else base_seed()
    return jax.random.PRNGKey(_mix(s, key))
