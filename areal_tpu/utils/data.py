"""Host-side batch containers: padded <-> packed conversion, micro-batching.

Capability parity with the reference's ``areal/utils/data.py`` (SURVEY §2.4):
``pad_sequences_to_tensors``, ``concat_padded_tensors``, ``pack_tensor_dict``,
``unpack_sequence``, ``split_padded_tensor_dict_into_mb_list``, ``pad_mb_list``,
``Normalization`` (group/batch mean-std) and ``KLEstimator`` (k1/k2/k3).

Design: trajectories travel between the rollout runtime and the train engine as
plain ``dict[str, np.ndarray]`` on host. Padded batches are ``[bs, seqlen]``
with an ``attention_mask``; the engine packs them into flat ``[total_tokens]``
arrays with ``cu_seqlens`` + per-token segment ids before anything is shipped
to the TPU (packing avoids MXU cycles on pad tokens, and static-shape padding
of each microbatch to a bucket size keeps XLA recompilation bounded).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from areal_tpu.utils import datapack

TensorDict = dict[str, Any]


def _is_per_token(key: str, arr: np.ndarray, batch_size: int) -> bool:
    return isinstance(arr, np.ndarray) and arr.ndim >= 2 and arr.shape[0] == batch_size


def pad_sequences_to_tensors(
    sequences: list[TensorDict], pad_value: float = 0.0
) -> TensorDict:
    """Stack a list of per-sequence dicts of 1D arrays into padded [bs, maxlen]
    arrays plus an ``attention_mask``. Scalar entries stack to [bs].

    Reference behavior: areal/utils/data.py:82.
    """
    if not sequences:
        return {}
    keys = sequences[0].keys()
    seq_keys = [
        k
        for k in keys
        if isinstance(sequences[0][k], np.ndarray) and sequences[0][k].ndim >= 1
    ]
    if not seq_keys:
        raise ValueError(
            "pad_sequences_to_tensors needs at least one ndarray (per-token) key "
            f"to derive sequence lengths; got keys {sorted(keys)}"
        )
    max_len = max(int(np.shape(s[seq_keys[0]])[0]) for s in sequences)
    out: TensorDict = {}
    for k in keys:
        v0 = sequences[0][k]
        if k in seq_keys:
            padded = []
            for s in sequences:
                arr = np.asarray(s[k])
                pad_width = [(0, max_len - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                padded.append(np.pad(arr, pad_width, constant_values=pad_value))
            out[k] = np.stack(padded)
        else:
            out[k] = np.asarray([s[k] for s in sequences])
    lens = np.asarray([int(np.shape(s[seq_keys[0]])[0]) for s in sequences])
    out["attention_mask"] = (np.arange(max_len)[None, :] < lens[:, None]).astype(
        np.bool_
    )
    return out


def concat_padded_tensors(
    tensor_dicts: list[TensorDict], pad_value: float = 0.0
) -> TensorDict:
    """Concatenate padded batches along batch dim, re-padding to the common max
    length (reference: areal/utils/data.py:152)."""
    tensor_dicts = [d for d in tensor_dicts if d]
    if not tensor_dicts:
        return {}
    assert all("attention_mask" in d for d in tensor_dicts)
    max_len = max(d["attention_mask"].shape[1] for d in tensor_dicts)
    out: TensorDict = {}
    keys = tensor_dicts[0].keys()
    for k in keys:
        parts = []
        for d in tensor_dicts:
            arr = np.asarray(d[k])
            bs = d["attention_mask"].shape[0]
            if _is_per_token(k, arr, bs) and arr.shape[1] == d["attention_mask"].shape[1]:
                pad_len = max_len - arr.shape[1]
                if pad_len:
                    value = False if arr.dtype == np.bool_ else pad_value
                    pad_width = [(0, 0), (0, pad_len)] + [(0, 0)] * (arr.ndim - 2)
                    arr = np.pad(arr, pad_width, constant_values=value)
            parts.append(arr)
        out[k] = np.concatenate(parts, axis=0)
    return out


def shuffle_within_batch(data: TensorDict, seed: int | None = None) -> TensorDict:
    bs = data["attention_mask"].shape[0]
    rng = np.random.default_rng(seed)
    perm = rng.permutation(bs)
    return index_select(data, perm)


def index_select(data: TensorDict, indices) -> TensorDict:
    indices = np.asarray(indices)
    bs = data["attention_mask"].shape[0]
    out = {}
    for k, v in data.items():
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == bs:
            out[k] = arr[indices]
        else:
            out[k] = arr
    return out


def batch_size_of(data: TensorDict) -> int:
    return int(data["attention_mask"].shape[0])


def seqlens_of(data: TensorDict) -> np.ndarray:
    return np.asarray(data["attention_mask"]).sum(axis=1).astype(np.int64)


def pack_tensor_dict(data: TensorDict) -> TensorDict:
    """Padded [bs, T] -> packed flat arrays.

    Returns a dict with every per-token key flattened to [total_tokens, ...],
    plus ``cu_seqlens`` [bs+1] and ``max_seqlen`` (host ints). Reference:
    areal/utils/data.py:266.
    """
    mask = np.asarray(data["attention_mask"]).astype(bool)
    bs, t = mask.shape
    lens = mask.sum(axis=1).astype(np.int32)
    cu = np.zeros(bs + 1, dtype=np.int32)
    np.cumsum(lens, out=cu[1:])
    flat_idx = np.nonzero(mask.reshape(-1))[0]
    out: TensorDict = {}
    for k, v in data.items():
        if k == "attention_mask":
            continue
        arr = np.asarray(v)
        if k == "pixel_values":
            out[k] = arr  # per-ROW image tensors ride alongside unpacked
            continue
        if _is_per_token(k, arr, bs) and arr.shape[1] == t:
            out[k] = arr.reshape((bs * t,) + arr.shape[2:])[flat_idx]
        else:
            out[k] = arr
    out["cu_seqlens"] = cu
    out["max_seqlen"] = int(lens.max()) if bs else 0
    return out


def unpack_sequence(packed: np.ndarray, cu_seqlens: np.ndarray) -> list[np.ndarray]:
    """Split a packed flat array back into per-sequence arrays
    (reference: areal/utils/data.py:224)."""
    return [
        packed[int(cu_seqlens[i]) : int(cu_seqlens[i + 1])]
        for i in range(len(cu_seqlens) - 1)
    ]


def unpack_to_padded(
    packed: np.ndarray, cu_seqlens: np.ndarray, pad_value: float = 0.0
) -> np.ndarray:
    seqs = unpack_sequence(packed, cu_seqlens)
    max_len = max((len(s) for s in seqs), default=0)
    out = np.full((len(seqs), max_len) + packed.shape[1:], pad_value, packed.dtype)
    for i, s in enumerate(seqs):
        out[i, : len(s)] = s
    return out


def segment_ids_from_cu_seqlens(cu_seqlens: np.ndarray, total: int | None = None):
    """Per-token segment ids (0-based) for packed attention; pad tokens get -1
    when ``total`` exceeds cu_seqlens[-1]."""
    n = int(cu_seqlens[-1])
    total = total if total is not None else n
    seg = np.full(total, -1, dtype=np.int32)
    for i in range(len(cu_seqlens) - 1):
        seg[int(cu_seqlens[i]) : int(cu_seqlens[i + 1])] = i
    return seg


def positions_from_cu_seqlens(cu_seqlens: np.ndarray, total: int | None = None):
    n = int(cu_seqlens[-1])
    total = total if total is not None else n
    pos = np.zeros(total, dtype=np.int32)
    for i in range(len(cu_seqlens) - 1):
        s, e = int(cu_seqlens[i]), int(cu_seqlens[i + 1])
        pos[s:e] = np.arange(e - s)
    return pos


@dataclasses.dataclass
class MicroBatchList:
    """A split of one padded batch into token-budgeted microbatches."""

    mbs: list[TensorDict]
    group_lens: list[int]  # total real tokens per microbatch
    forward_indices: list[list[int]]  # original row idx per mb
    padded_to: list[int] = dataclasses.field(default_factory=list)

    @property
    def n_mbs(self) -> int:
        return len(self.mbs)

    def reorder_back(self, per_row_outputs: list[Any]) -> list[Any]:
        """Given outputs per mb-row (concatenated in mb order), restore the
        original batch row order."""
        flat_idx = datapack.flat2d(self.forward_indices)
        out = [None] * len(flat_idx)
        for pos, orig in enumerate(flat_idx):
            out[orig] = per_row_outputs[pos]
        return out


def split_padded_tensor_dict_into_mb_list(
    data: TensorDict,
    max_tokens_per_mb: int,
    min_n_mbs: int = 1,
    group_size: int = 1,
) -> MicroBatchList:
    """FFD-split a padded batch into microbatches under a token budget
    (reference: areal/utils/data.py:404).

    ``group_size > 1`` keeps each block of ``group_size`` consecutive rows in
    the same microbatch, in order — pairwise losses (reward models) and
    group-relative advantages rely on adjacency."""
    lens = seqlens_of(data)
    if group_size > 1:
        assert len(lens) % group_size == 0, (len(lens), group_size)
        unit_lens = lens.reshape(-1, group_size).sum(axis=1)
        unit_bins = datapack.ffd_allocate(
            unit_lens, max_tokens_per_mb, min_groups=min_n_mbs
        )
        bins = [
            [u * group_size + j for u in b for j in range(group_size)]
            for b in unit_bins
        ]
    else:
        bins = datapack.ffd_allocate(lens, max_tokens_per_mb, min_groups=min_n_mbs)
    # drop empty bins: an empty microbatch has zero loss weight and would
    # poison the global normalizer (min_n_mbs is a target, not a guarantee —
    # a batch smaller than min_n_mbs yields fewer microbatches)
    bins = [b for b in bins if b] or [[]]
    mbs = []
    group_lens = []
    for b in bins:
        mbs.append(index_select(data, np.asarray(b, dtype=np.int64)))
        group_lens.append(int(lens[b].sum()))
    return MicroBatchList(mbs=mbs, group_lens=group_lens, forward_indices=bins)


def pad_packed_to_multiple(packed: TensorDict, multiple: int, pad_token: int = 0):
    """Pad a packed batch's flat arrays up to a multiple of ``multiple`` tokens
    by appending a dummy sequence; keeps XLA shapes bucketed (reference's
    pad_mb_list pads for TP/CP alignment, areal/utils/data.py:685)."""
    cu = packed["cu_seqlens"]
    n = int(cu[-1])
    target = ((n + multiple - 1) // multiple) * multiple
    pad = target - n
    if pad == 0:
        return packed, n
    out = dict(packed)
    for k, v in packed.items():
        if k in ("cu_seqlens", "max_seqlen"):
            continue
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == n:
            value = pad_token if k == "input_ids" else 0
            pad_width = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
            out[k] = np.pad(arr, pad_width, constant_values=value)
    out["cu_seqlens"] = np.concatenate([cu, [target]]).astype(np.int32)
    out["max_seqlen"] = max(int(packed["max_seqlen"]), pad)
    return out, n


def cycle_dataloader(loader):
    """Infinite epoch-cycling iterator (reference: areal/utils/data.py:1063)."""
    while True:
        yield from loader


@dataclasses.dataclass
class Normalization:
    """Advantage normalization: none / batch / group mean-std
    (reference: areal/utils/data.py:1073).

    group_size partitions the batch rows into consecutive groups (GRPO's
    n-samples-per-prompt groups).
    """

    mean_level: str = "batch"  # "batch" | "group" | "none"
    std_level: str = "batch"  # "batch" | "group" | "none"
    group_size: int = 1
    eps: float = 1e-5
    # RLOO-style leave-one-out mean: sample i's baseline is the mean over
    # the OTHER members of its normalization scope (reference NormConfig)
    mean_leave1out: bool = False
    std_unbiased: bool = False  # Bessel (n-1) std

    def __call__(
        self, x: np.ndarray, mask: np.ndarray | None = None
    ) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        if mask is None:
            mask = np.ones_like(x, dtype=bool)
        mask = np.asarray(mask, dtype=bool)
        if not mask.any():
            return x.astype(np.float32)

        def scope_mean(values, m, axes):
            """Per-element mean over ``axes`` (plain or leave-one-out),
            broadcast to values.shape. Reference semantics
            (areal/utils/data.py:1206-1262): masked-out elements see the
            plain mean; a scope with <=1 active elements gets mean 0 under
            leave-one-out."""
            cnt = m.sum(axis=axes, keepdims=True).astype(np.float64)
            tot = (values * m).sum(axis=axes, keepdims=True)
            reg = np.where(cnt > 0, tot / np.maximum(cnt, 1.0), 0.0)
            if not self.mean_leave1out:
                return np.broadcast_to(reg, values.shape)
            loo = (tot - values * m) / np.maximum(cnt - m, 1.0)
            mean = np.where(m, loo, np.broadcast_to(reg, values.shape))
            return np.where(cnt > 1, mean, 0.0)

        def scope_std(values, m, mean, axes):
            """Std over ``axes`` around the (possibly per-element) ``mean``
            actually subtracted in step 1 — the reference computes squared
            deviations from that mean, not from the plain scope mean."""
            cnt = m.sum(axis=axes, keepdims=True).astype(np.float64)
            centered = (values - mean) * m
            denom = (
                np.maximum(cnt - 1, 1.0)
                if self.std_unbiased
                else np.maximum(cnt, 1.0)
            )
            var = (centered**2).sum(axis=axes, keepdims=True) / denom
            return np.broadcast_to(np.sqrt(var), values.shape)

        need_group = self.mean_level == "group" or self.std_level == "group"
        if need_group:
            bs = x.shape[0]
            assert bs % self.group_size == 0, (bs, self.group_size)
            gshape = (bs // self.group_size, self.group_size) + x.shape[1:]
            g = x.reshape(gshape)
            gm = mask.reshape(gshape)
            gaxes = tuple(range(1, g.ndim))

        # step 1: the mean that gets subtracted (zeros when mean_level=none)
        if self.mean_level == "group":
            if self.group_size == 1 and self.mean_leave1out:
                mean = np.zeros_like(x)  # reference special case
            else:
                mean = scope_mean(g, gm, gaxes).reshape(x.shape)
        elif self.mean_level == "batch":
            mean = scope_mean(x, mask, tuple(range(x.ndim)))
        else:
            mean = np.zeros_like(x)

        x_centered = (x - mean) * mask

        # step 2: std around the step-1 mean (whatever its level was)
        eps = self.eps
        if self.std_level == "group":
            if self.group_size == 1 and self.std_unbiased:
                std = np.ones_like(x)  # reference special case (n-1 == 0)
            else:
                std = scope_std(
                    g, gm, mean.reshape(gshape), gaxes
                ).reshape(x.shape)
        elif self.std_level == "batch":
            std = scope_std(x, mask, mean, tuple(range(x.ndim)))
        else:
            std = np.ones_like(x)
            eps = 0.0
        return (x_centered / (std + eps)).astype(np.float32)


@dataclasses.dataclass
class KLEstimator:
    """k1/k2/k3 KL estimators (http://joschu.net/blog/kl-approx.html);
    reference: areal/utils/data.py:1306."""

    kind: str = "k1"

    def __call__(self, logp: np.ndarray, ref_logp: np.ndarray) -> np.ndarray:
        logr = ref_logp - logp
        if self.kind == "k1":
            return -logr
        if self.kind == "k2":
            return 0.5 * logr**2
        if self.kind == "k3":
            return np.expm1(logr) - logr
        raise ValueError(f"Unknown KL estimator: {self.kind}")


def to_device_tree(data: TensorDict):
    """Convert numpy leaves to jax arrays (lazy import)."""
    import jax.numpy as jnp

    return {
        k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
        for k, v in data.items()
    }
