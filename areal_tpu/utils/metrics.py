"""Unified metrics plane: one registry for every counter the system
already keeps in ad-hoc dicts.

Before this module, the same fact lived in several places with several
shapes — ``serving_stats()`` in ``/model_info``, ServerHealthTracker's
sliding windows, StalenessManager counters, ``weight_sync_*`` attributes,
spec-decode acceptance — and nothing could *scrape* them. This registry
gives them one home with three instrument types:

- :class:`Counter` — monotonically increasing totals;
- :class:`Gauge` — point-in-time values (queue depth, blocks free);
- :class:`Histogram` — bucketed distributions with ``quantile()``
  estimation (p50/p95/p99 TTFT and inter-token latency).

plus **collector callbacks**: a component registers a function that is
invoked at scrape/export time to copy its live counters into gauges, so
``/metrics`` always agrees with ``/model_info`` by construction (they
read the same source at the same moment) and steady-state cost is zero.

Exposition: :meth:`MetricsRegistry.render_prometheus` emits the
Prometheus text format (``/metrics`` on the inference server);
:meth:`MetricsRegistry.export_scalars` flattens to a ``dict[str, float]``
for the trainer-side StatsLogger periodic export.

**Label-cardinality guard**: metric labels multiply time series, and an
unbounded label value (a raw rid, a uuid) grows the registry without
limit — the classic Prometheus cardinality explosion. Each metric caps
its distinct label-sets at ``max_label_values``; past the cap, new label
values coalesce into ``"__overflow__"`` (logged once). The static side
is enforced by the ``unbounded-metric-label`` arealint rule.

Thread-safe throughout; the per-child fast path after the first
``labels()`` call is one dict probe.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
import time

from areal_tpu.utils import logging

logger = logging.getLogger("metrics")

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

OVERFLOW_LABEL = "__overflow__"

#: default latency buckets (seconds): sub-ms to minutes, log-ish spacing
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple):
        self._lock = threading.Lock()
        self.buckets = buckets  # sorted upper bounds, +Inf implicit
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def observe_many(self, values) -> None:
        """Bulk observation for per-batch array telemetry (the RL-health
        observatory feeds thousands of per-token values once per step): one
        bucketize pass + one lock acquisition, instead of a python loop of
        per-value ``observe`` calls each taking the lock.

        Non-finite values are DROPPED: one NaN would stick in ``sum``
        forever and poison every later scrape of ``<name>_sum`` — and the
        diverging-run regime is exactly when these histograms must stay
        readable (the sentinel reports the non-finite value itself through
        its own rules)."""
        import numpy as np

        vals = np.asarray(values, dtype=np.float64).reshape(-1)
        vals = vals[np.isfinite(vals)]
        if vals.size == 0:
            return
        # side="left" matches bisect_left in observe(): value == bound
        # lands IN that bucket (prometheus le semantics)
        idx = np.searchsorted(self.buckets, vals, side="left")
        binned = np.bincount(idx, minlength=len(self.buckets) + 1)
        total = float(vals.sum())
        with self._lock:
            for i, c in enumerate(binned):
                if c:
                    self.counts[i] += int(c)
            self.sum += total
            self.count += int(vals.size)

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (the scrape-side
        ``histogram_quantile`` computation, available in-process so the
        fleet summary and tests don't need a Prometheus server).

        Estimates are capped at the largest finite bucket bound
        (Prometheus convention) — check :attr:`overflow_count` to tell a
        true 120s tail from ">120s, capped"."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else self.buckets[-1]
                )
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.buckets[-1]

    @property
    def overflow_count(self) -> int:
        """Observations beyond the largest finite bucket. Nonzero means
        ``quantile()`` estimates touching the last bucket understate the
        real tail."""
        with self._lock:
            return self.counts[-1]


_CHILD_TYPES = {
    "counter": _CounterChild,
    "gauge": _GaugeChild,
    "histogram": _HistogramChild,
}


class _Metric:
    """One named metric family; children keyed by label-value tuples."""

    def __init__(
        self,
        name: str,
        help: str,
        kind: str,
        labelnames: tuple,
        max_label_values: int,
        buckets: tuple = DEFAULT_BUCKETS,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets))
        self._max_label_values = max_label_values
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        self._overflowed = False
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self.buckets)
        return _CHILD_TYPES[self.kind]()

    def labels(self, **labelvalues):
        """Child for one label-set. Distinct label-sets are capped at
        ``max_label_values``: past the cap, new values coalesce into the
        ``__overflow__`` series — a raw rid/uuid label can degrade the
        metric, never the process."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(key)
            if child is not None:
                return child
            if len(self._children) >= self._max_label_values:
                if not self._overflowed:
                    self._overflowed = True
                    logger.warning(
                        "metric %s exceeded %d distinct label sets "
                        "(unbounded label value? e.g. a raw rid); new "
                        "series coalesce into %s",
                        self.name,
                        self._max_label_values,
                        OVERFLOW_LABEL,
                    )
                key = (OVERFLOW_LABEL,) * len(self.labelnames)
                child = self._children.get(key)
                if child is not None:
                    return child
            child = self._new_child()
            self._children[key] = child
            return child

    # unlabelled conveniences ------------------------------------------

    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} is labelled {self.labelnames}; "
                "call .labels(...) first"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def observe_many(self, values) -> None:
        self._solo().observe_many(values)

    def quantile(self, q: float) -> float:
        return self._solo().quantile(q)

    @property
    def value(self) -> float:
        return self._solo().value

    def children(self) -> dict[tuple, object]:
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    def __init__(self, max_label_values: int = 128, clock=time.monotonic):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []
        self._initial_max_label_values = max_label_values
        self.max_label_values = max_label_values
        self.clock = clock

    def set_max_label_values(self, n: int) -> None:
        """Re-cap label cardinality (MetricsConfig.max_label_values): the
        process-global registry is built at import time, so config lands
        after metrics already exist — retune them too, not just future
        ones. Shrinking below a metric's live child count keeps existing
        children and only coalesces NEW values into ``__overflow__``."""
        with self._lock:
            self.max_label_values = int(n)
            for m in self._metrics.values():
                m._max_label_values = int(n)

    # -- instrument factories (get-or-create, type-checked) -------------

    def _get_or_create(
        self, name: str, help: str, kind: str, labels: tuple, buckets: tuple
    ) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name} already registered as {m.kind}"
                        f"{m.labelnames}, requested {kind}{tuple(labels)}"
                    )
                return m
            m = _Metric(
                name, help, kind, tuple(labels), self.max_label_values,
                buckets,
            )
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels: tuple = ()) -> _Metric:
        return self._get_or_create(name, help, "counter", labels, ())

    def gauge(self, name: str, help: str = "", labels: tuple = ()) -> _Metric:
        return self._get_or_create(name, help, "gauge", labels, ())

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: tuple = (),
        buckets: tuple = DEFAULT_BUCKETS,
    ) -> _Metric:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    # -- collectors -----------------------------------------------------

    def register_collector(self, fn) -> object:
        """``fn(registry)`` runs right before every render/export,
        copying a component's live counters into gauges — the scrape and
        the component's own API read the same values at the same moment.
        Returns a handle for :meth:`unregister_collector`."""
        with self._lock:
            self._collectors.append(fn)
        return fn

    def unregister_collector(self, handle) -> None:
        with self._lock:
            try:
                self._collectors.remove(handle)
            except ValueError:
                pass

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # a sick collector must not kill the scrape
                logger.exception("metrics collector failed")

    # -- export ---------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._run_collectors()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help or m.name}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, child in sorted(m.children().items()):
                base_lbl = ",".join(
                    f'{ln}="{_escape_label_value(lv)}"'
                    for ln, lv in zip(m.labelnames, key)
                )
                if m.kind == "histogram":
                    cum = 0
                    for i, ub in enumerate(child.buckets):
                        cum += child.counts[i]
                        le = f'le="{_fmt(ub)}"'
                        lbl = f"{base_lbl},{le}" if base_lbl else le
                        lines.append(
                            f"{m.name}_bucket{{{lbl}}} {cum}"
                        )
                    cum += child.counts[-1]
                    le = 'le="+Inf"'
                    lbl = f"{base_lbl},{le}" if base_lbl else le
                    lines.append(f"{m.name}_bucket{{{lbl}}} {cum}")
                    suffix = f"{{{base_lbl}}}" if base_lbl else ""
                    lines.append(f"{m.name}_sum{suffix} {_fmt(child.sum)}")
                    lines.append(f"{m.name}_count{suffix} {cum}")
                else:
                    suffix = f"{{{base_lbl}}}" if base_lbl else ""
                    lines.append(f"{m.name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"

    def export_scalars(self, prefix: str = "") -> dict[str, float]:
        """Flatten to ``{name{labels}: value}`` floats for the
        StatsLogger periodic export; histograms export count/sum and
        p50/p95/p99."""
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, float] = {}
        for m in metrics:
            for key, child in m.children().items():
                lbl = (
                    "{" + ",".join(
                        f"{ln}={lv}" for ln, lv in zip(m.labelnames, key)
                    ) + "}"
                    if key
                    else ""
                )
                base = f"{prefix}{m.name}{lbl}"
                if m.kind == "histogram":
                    out[f"{base}/count"] = float(child.count)
                    out[f"{base}/sum"] = float(child.sum)
                    out[f"{base}/p50"] = child.quantile(0.50)
                    out[f"{base}/p95"] = child.quantile(0.95)
                    out[f"{base}/p99"] = child.quantile(0.99)
                    ovf = child.overflow_count
                    if ovf:
                        # quantiles above are capped at the largest
                        # finite bucket; this says how many observations
                        # landed past it
                        out[f"{base}/overflow_count"] = float(ovf)
                else:
                    out[base] = float(child.value)
        return out

    def reset(self) -> None:
        """Drop every metric and collector and restore the construction-time
        label cap (test isolation — a retuned cap must not leak)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()
            self.max_label_values = self._initial_max_label_values


DEFAULT_REGISTRY = MetricsRegistry()

counter = DEFAULT_REGISTRY.counter
gauge = DEFAULT_REGISTRY.gauge
histogram = DEFAULT_REGISTRY.histogram
register_collector = DEFAULT_REGISTRY.register_collector
unregister_collector = DEFAULT_REGISTRY.unregister_collector
render_prometheus = DEFAULT_REGISTRY.render_prometheus
export_scalars = DEFAULT_REGISTRY.export_scalars


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Minimal parser of the text exposition format (tests + the
    ``/metrics``-agrees-with-``/model_info`` gate): returns
    ``{"name{labels}": value}``; raises ValueError on malformed lines so
    a garbled exposition fails loudly."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
        except ValueError:
            raise ValueError(f"malformed metrics line: {line!r}") from None
        if not series or (
            "{" in series and not series.endswith("}")
        ):
            raise ValueError(f"malformed metrics line: {line!r}")
        v = float(value) if value != "+Inf" else math.inf
        out[series] = v
    return out
