"""Checkpointable shuffling dataloader.

The reference uses torchdata's StatefulDataLoader (areal/utils/dataloader.py)
for exactly-resumable iteration; this is a dependency-free equivalent: epoch-
seeded shuffling, per-DP-rank batches, and a ``state_dict`` that fast-forwards
to the same position after recovery.

Elastic resume: the cursor is a SAMPLE index into the (seed, epoch)-shuffled
order — which depends only on the dataset and seed, never on how samples are
grouped into batches. A checkpoint written at batch size B therefore resumes
correctly at any batch size B' (a replacement trainer with a different host
count consumes a different global batch): the stream of samples continues
exactly where it stopped, replaying none and skipping none. The refusal path
survives only for genuinely incompatible changes — a different dataset makes
the saved shuffle order and cursor meaningless — and names the exact
mismatched field. Legacy batch-cursor states (``batch_in_epoch``) remap via
their saved batch size.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterator, Sequence


class IncompatibleResumeState(ValueError):
    """The saved dataloader state cannot be remapped onto this loader.
    The message names the exact incompatible field."""


class StatefulDataLoader:
    def __init__(
        self,
        dataset: Sequence[Any],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Callable[[list], Any] | None = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or (lambda x: x)
        self._epoch = 0
        #: SAMPLE index into the epoch's shuffled order (batch-size
        #: independent — the whole elastic-resume seam)
        self._sample_in_epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _order(self, epoch: int) -> list[int]:
        idx = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random((self.seed, epoch).__hash__()).shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[Any]:
        """Yields the REMAINDER of the current epoch (so a freshly restored
        loader resumes mid-epoch), then advances the epoch counter. Callers
        loop epochs by re-iterating (see utils.data.cycle_dataloader).

        With ``drop_last``, a tail of fewer than ``batch_size`` samples is
        dropped at the epoch boundary — the standard contract. After an
        elastic resume whose new batch size doesn't divide the remaining
        sample count, that rule applies to the (possibly nonempty) tail the
        same way it applies to an uninterrupted epoch."""
        order = self._order(self._epoch)
        n = len(order)
        while self._sample_in_epoch < n:
            s = self._sample_in_epoch
            take = min(self.batch_size, n - s)
            if self.drop_last and take < self.batch_size:
                break
            sel = order[s : s + take]
            self._sample_in_epoch = s + take
            yield self.collate_fn([self.dataset[i] for i in sel])
        self._epoch += 1
        self._sample_in_epoch = 0

    def state_dict(self) -> dict:
        return {
            "epoch": self._epoch,
            "sample_in_epoch": self._sample_in_epoch,
            "seed": self.seed,
            # resume-safety fingerprint: the cursor is an index into the
            # (seed, epoch)-shuffled order of THIS dataset — restoring it
            # over a different dataset silently trains on the wrong sample
            # stream. batch_size rides along for observability and legacy
            # remap, but is NOT part of the compatibility contract.
            "dataset_size": len(self.dataset),
            "batch_size": self.batch_size,
        }

    def load_state_dict(self, state: dict):
        size = state.get("dataset_size")
        if size is not None and size != len(self.dataset):
            raise IncompatibleResumeState(
                f"refusing to restore dataloader cursor: dataset_size "
                f"mismatch — saved {size}, current {len(self.dataset)} "
                "(the dataset changed; the saved shuffle order and cursor "
                "are meaningless)"
            )
        if "sample_in_epoch" in state:
            sample = int(state["sample_in_epoch"])
        else:
            # legacy batch-cursor state: remap batches -> samples via the
            # batch size the cursor was counted in
            saved_bs = state.get("batch_size")
            if saved_bs is None:
                raise IncompatibleResumeState(
                    "refusing to restore dataloader cursor: legacy state "
                    "has batch_in_epoch but no batch_size to remap it with"
                )
            sample = int(state["batch_in_epoch"]) * int(saved_bs)
        if sample > len(self.dataset):
            raise IncompatibleResumeState(
                f"refusing to restore dataloader cursor: sample_in_epoch "
                f"{sample} exceeds dataset_size {len(self.dataset)}"
            )
        self._epoch = int(state["epoch"])
        self._sample_in_epoch = sample
        self.seed = state.get("seed", self.seed)
