"""Checkpointable shuffling dataloader.

The reference uses torchdata's StatefulDataLoader (areal/utils/dataloader.py)
for exactly-resumable iteration; this is a dependency-free equivalent: epoch-
seeded shuffling, per-DP-rank batches, and a ``state_dict`` that fast-forwards
to the same (epoch, batch) position after recovery.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterator, Sequence


class StatefulDataLoader:
    def __init__(
        self,
        dataset: Sequence[Any],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Callable[[list], Any] | None = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or (lambda x: x)
        self._epoch = 0
        self._batch_in_epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _order(self, epoch: int) -> list[int]:
        idx = list(range(len(self.dataset)))
        if self.shuffle:
            random.Random((self.seed, epoch).__hash__()).shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[Any]:
        """Yields the REMAINDER of the current epoch (so a freshly restored
        loader resumes mid-epoch), then advances the epoch counter. Callers
        loop epochs by re-iterating (see utils.data.cycle_dataloader)."""
        order = self._order(self._epoch)
        nb = len(self)
        while self._batch_in_epoch < nb:
            b = self._batch_in_epoch
            sel = order[b * self.batch_size : (b + 1) * self.batch_size]
            self._batch_in_epoch += 1
            yield self.collate_fn([self.dataset[i] for i in sel])
        self._epoch += 1
        self._batch_in_epoch = 0

    def state_dict(self) -> dict:
        return {
            "epoch": self._epoch,
            "batch_in_epoch": self._batch_in_epoch,
            "seed": self.seed,
            # resume-safety fingerprint: the cursor is an index into the
            # (seed, epoch)-shuffled order of THIS dataset — restoring it
            # over a different dataset/batching silently trains on the
            # wrong sample stream
            "dataset_size": len(self.dataset),
            "batch_size": self.batch_size,
        }

    def load_state_dict(self, state: dict):
        size = state.get("dataset_size")
        if size is not None and size != len(self.dataset):
            raise ValueError(
                f"refusing to restore dataloader cursor: dataset has "
                f"{len(self.dataset)} rows, saved state was over {size} "
                "(the dataset changed; the saved shuffle order and cursor "
                "are meaningless)"
            )
        bs = state.get("batch_size")
        if bs is not None and bs != self.batch_size:
            raise ValueError(
                f"refusing to restore dataloader cursor: batch_size "
                f"{self.batch_size} != saved {bs}"
            )
        self._epoch = state["epoch"]
        self._batch_in_epoch = state["batch_in_epoch"]
        self.seed = state.get("seed", self.seed)
