"""Sequence bin-packing / balanced partition.

Capability parity with the reference's ``areal/utils/datapack.py``:
``ffd_allocate`` (first-fit-decreasing under a token budget with a min-group
constraint, datapack.py:187) and ``partition_balanced`` (DP-balanced
partitioning, datapack.py:14). Implementations are our own.

These run on host (they shape microbatches before anything touches the TPU);
a C++ fast path is provided via ``areal_tpu.utils.native`` when built.
"""

from __future__ import annotations

import numpy as np

from areal_tpu.utils import native


def ffd_allocate(
    sizes: list[int] | np.ndarray,
    capacity: int,
    min_groups: int = 1,
) -> list[list[int]]:
    """First-fit-decreasing: pack items (token counts) into the fewest bins of
    ``capacity`` tokens, then split further if fewer than ``min_groups`` bins.

    Returns a list of bins, each a list of original item indices. Every item
    must individually fit in ``capacity``.
    """
    sizes = np.asarray(sizes, dtype=np.int64)
    if len(sizes) == 0:
        return [[] for _ in range(min_groups)]
    if sizes.max() > capacity:
        raise ValueError(
            f"Item of size {int(sizes.max())} exceeds bin capacity {capacity}"
        )
    native_result = native.ffd_group_ids(sizes, capacity)
    if native_result is not None:
        n_bins, gids = native_result
        bins = [[] for _ in range(n_bins)]
        loads = [0] * n_bins
        for i, g in enumerate(gids.tolist()):
            bins[g].append(i)
            loads[g] += int(sizes[i])
    else:
        order = np.argsort(-sizes, kind="stable")
        bins = []
        loads = []
        for idx in order:
            size = int(sizes[idx])
            placed = False
            for b in range(len(bins)):
                if loads[b] + size <= capacity:
                    bins[b].append(int(idx))
                    loads[b] += size
                    placed = True
                    break
            if not placed:
                bins.append([int(idx)])
                loads.append(size)
    while len(bins) < min_groups:
        # split the heaviest multi-item bin
        cand = sorted(
            (b for b in range(len(bins)) if len(bins[b]) > 1),
            key=lambda b: -loads[b],
        )
        if not cand:
            bins.append([])
            loads.append(0)
            continue
        b = cand[0]
        items = sorted(bins[b], key=lambda i: -int(sizes[i]))
        half_a, half_b, la, lb = [], [], 0, 0
        for i in items:
            if la <= lb:
                half_a.append(i)
                la += int(sizes[i])
            else:
                half_b.append(i)
                lb += int(sizes[i])
        bins[b] = half_a
        loads[b] = la
        bins.append(half_b)
        loads.append(lb)
    # keep deterministic order: sort each bin & sort bins by first item
    bins = [sorted(b) for b in bins]
    bins.sort(key=lambda b: (b[0] if b else 1 << 62))
    return bins


def partition_balanced(sizes: list[int] | np.ndarray, k: int) -> list[list[int]]:
    """Partition ``len(sizes)`` contiguously-indexed items into exactly ``k``
    groups minimizing the max group load (greedy LPT, then index-sorted).

    Unlike ``ffd_allocate`` this always returns exactly k groups and has no
    capacity limit — used for DP-rank balancing (reference datapack.py:14).
    """
    sizes = np.asarray(sizes, dtype=np.int64)
    n = len(sizes)
    if k <= 0:
        raise ValueError("k must be positive")
    gids = native.partition_group_ids(sizes, k)
    groups: list[list[int]] = [[] for _ in range(k)]
    if gids is not None:
        for i, g in enumerate(gids.tolist()):
            groups[g].append(i)
    else:
        loads = np.zeros(k, dtype=np.int64)
        order = np.argsort(-sizes, kind="stable")
        for idx in order:
            b = int(np.argmin(loads))
            groups[b].append(int(idx))
            loads[b] += int(sizes[idx])
    for g in groups:
        g.sort()
    if n >= k and any(len(g) == 0 for g in groups):
        # steal from the largest group to guarantee non-empty groups
        for b in range(k):
            if not groups[b]:
                donor = max(range(k), key=lambda j: len(groups[j]))
                groups[b].append(groups[donor].pop())
        for g in groups:
            g.sort()
    return groups


def flat2d(list_of_lists):
    return [x for sub in list_of_lists for x in sub]
