"""Canonical name-resolve key layout.

Parity: reference ``areal/utils/names.py`` — every distributed component
registers/watches keys under a trial-scoped prefix.
"""

from __future__ import annotations

ROOT = "areal_tpu"


def trial_root(experiment_name: str, trial_name: str) -> str:
    return f"{ROOT}/{experiment_name}/{trial_name}"


def gen_servers(experiment_name: str, trial_name: str) -> str:
    """Subtree under which inference servers register their addresses."""
    return f"{trial_root(experiment_name, trial_name)}/gen_servers"


def gen_server(experiment_name: str, trial_name: str, server_id: str) -> str:
    return f"{gen_servers(experiment_name, trial_name)}/{server_id}"


def gen_server_roles(experiment_name: str, trial_name: str) -> str:
    """Subtree under which inference servers register their serving ROLE
    ("prefill" | "decode"; generalists register nothing). Keyed by the
    same server_id as :func:`gen_server` so the client's role-aware
    router and the fleet controller's per-role pools can join the two
    subtrees. Deliberately OUTSIDE ``gen_servers`` so role tags are never
    resolved as server addresses."""
    return f"{trial_root(experiment_name, trial_name)}/gen_server_roles"


def gen_server_role(experiment_name: str, trial_name: str, server_id: str) -> str:
    return f"{gen_server_roles(experiment_name, trial_name)}/{server_id}"


def gen_server_drain(experiment_name: str, trial_name: str, server_id: str) -> str:
    """Per-server drain request key (elastic fleet scale-in of a server the
    controller did not spawn): the server watches its own key and exits
    gracefully when it appears. Deliberately OUTSIDE the ``gen_servers``
    subtree so drain markers are never resolved as server addresses."""
    return f"{trial_root(experiment_name, trial_name)}/gen_server_drain/{server_id}"


def reward_services(experiment_name: str, trial_name: str) -> str:
    """Subtree under which reward-service replicas register their
    addresses (discovered by RewardServiceClient)."""
    return f"{trial_root(experiment_name, trial_name)}/reward_services"


def reward_service(experiment_name: str, trial_name: str, service_id: str) -> str:
    return f"{reward_services(experiment_name, trial_name)}/{service_id}"


def update_weights_from_disk(
    experiment_name: str, trial_name: str, model_version: int
) -> str:
    """Timestamp key used to measure disk weight-update latency
    (reference: areal/core/remote_inf_engine.py:762-810)."""
    return f"{trial_root(experiment_name, trial_name)}/update_weights_from_disk/{model_version}"


def weight_version(experiment_name: str, trial_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/weight_version"


def trainer_port(experiment_name: str, trial_name: str, role: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/trainer_ports/{role}"


def distributed_lock(experiment_name: str, trial_name: str, lock_name: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/locks/{lock_name}"


def worker_status(experiment_name: str, trial_name: str, worker: str) -> str:
    return f"{trial_root(experiment_name, trial_name)}/worker_status/{worker}"


def rl_health(experiment_name: str, trial_name: str) -> str:
    """Trainer-published RL-health status JSON (last step's headline
    signals + last anomaly), read by the ``areal-tpu-top`` operator CLI."""
    return f"{trial_root(experiment_name, trial_name)}/rl_health"
