"""Test fixtures: toy tokenizer, tiny on-disk model, synthetic math data.

The analogue of the reference's small-model testing kit
(realhf/base/testing.py:37-43 + the random-jsonl dataset fixtures in
realhf/tests/experiments): everything runs offline — the tokenizer is trained
in-process on a tiny corpus (no hub access), the model is a tiny random
checkpoint in HF layout, and the dataset is synthetic single-digit arithmetic
whose gold answers the math reward can verify.
"""

from __future__ import annotations

import json
import os
import random

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|im_start|>{{ message['role'] }}\n{{ message['content'] }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)

_CORPUS = [
    "What is 3 + 4? The answer is #### 7",
    "Compute 12 - 5. #### 7 dollars",
    "If x = 2 and y = 9 then x * y = #### 18",
    "0 1 2 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18",
    "abcdefghijklmnopqrstuvwxyz ABCDEFGHIJKLMNOPQRSTUVWXYZ",
    ".,;:!?()[]{}<>/*-+=#$%&@'\"\\ \n",
]


def make_toy_tokenizer(out_dir: str, vocab_size: int = 256):
    """Train a byte-level BPE in-process and save it as a
    PreTrainedTokenizerFast directory with a Qwen-style chat template."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<|endoftext|>", "<|im_start|>", "<|im_end|>", "<|pad|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(_CORPUS, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        eos_token="<|im_end|>",
        pad_token="<|pad|>",
        bos_token=None,
    )
    fast.chat_template = CHAT_TEMPLATE
    os.makedirs(out_dir, exist_ok=True)
    fast.save_pretrained(out_dir)
    return fast


def save_tiny_model(
    out_dir: str,
    vocab_size: int = 512,
    hidden_size: int = 32,
    num_hidden_layers: int = 2,
    seed: int = 0,
    **kw,
):
    """Random tiny HF-layout checkpoint (config.json + safetensors)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models import hf_io
    from areal_tpu.models.config import tiny_config

    cfg = tiny_config(
        vocab_size=vocab_size,
        hidden_size=hidden_size,
        intermediate_size=hidden_size * 2,
        num_hidden_layers=num_hidden_layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        **kw,
    )
    from areal_tpu.models.lm import init_params

    params = init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    hf_io.save_hf_params(params, cfg, out_dir)
    return cfg


def make_math_jsonl(path: str, n: int = 64, seed: int = 0):
    """Synthetic gsm8k-style rows: {question, answer: '... #### gold'}."""
    rng = random.Random(seed)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for _ in range(n):
            a, b = rng.randint(0, 9), rng.randint(0, 9)
            f.write(
                json.dumps(
                    {
                        "question": f"What is {a} + {b}?",
                        "answer": f"The answer is #### {a + b}",
                    }
                )
                + "\n"
            )
    return path


def make_clevr_jsonl(
    path: str, n: int = 16, image_size: int = 16, max_objects: int = 4, seed: int = 0
):
    """Synthetic clevr_count-style VLM rows: k bright squares on a dark
    field; question asks how many; answer = k. Images travel as base64
    (utils/image.py)."""
    import json

    import numpy as np

    from areal_tpu.utils.image import encode_image

    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            k = int(rng.integers(1, max_objects + 1))
            img = np.zeros((image_size, image_size, 3), np.float32)
            for _j in range(k):
                x = int(rng.integers(0, image_size - 3))
                y = int(rng.integers(0, image_size - 3))
                img[y : y + 3, x : x + 3] = rng.uniform(0.5, 1.0, 3)
            row = {
                "question": "How many objects are in the picture?",
                "images": [encode_image(img)],
                "answer": k,
            }
            f.write(json.dumps(row) + "\n")
