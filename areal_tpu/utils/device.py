"""Device/platform helpers.

The TPU images ship a sitecustomize that force-registers the TPU plugin and
ignores ``JAX_PLATFORMS`` from the environment, so subprocesses (tests, CPU
verification drives, CI) need an explicit override: set ``AREAL_PLATFORM=cpu``
and call :func:`apply_platform_env` before any jax computation. Entry points
(launchers, example scripts) all call it first thing.
"""

from __future__ import annotations

import os


def apply_platform_env():
    """Honor AREAL_PLATFORM / AREAL_HOST_DEVICE_COUNT before jax is used."""
    plat = os.environ.get("AREAL_PLATFORM")
    n = os.environ.get("AREAL_HOST_DEVICE_COUNT")
    if n:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        import jax

        jax.config.update("jax_platforms", plat)


def log_device_stats(tag: str = ""):
    """HBM usage snapshot (reference: areal/utils/device.py log_gpu_stats)."""
    import jax

    from areal_tpu.utils import logging

    logger = logging.getLogger("device")
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        used = stats.get("bytes_in_use", 0) / 1e9
        limit = stats.get("bytes_limit", 0) / 1e9
        logger.info("%s %s: %.2f/%.2f GB in use", tag, d, used, limit)
