"""Topology-independent checkpoint format: per-leaf shard files plus a
digest manifest, re-shardable into any mesh on restore.

The PR 4 recover dump pinned step-exact resume, but only at the SAME
topology: orbax's StandardCheckpointer restores into the sharding layout
of the restore target, and the loop around it assumed the replacement
trainer has the shape the dead one had. On preemptible pods the
replacement slice routinely does not. This module is the seam that makes
recovery elastic:

- **save**: every leaf of a named tree is written as one file per
  addressable shard (replica 0 only — replicated shards carry identical
  bytes), each file raw little-endian bytes written via the PR 4 atomic
  helpers. A ``manifest.json`` — written atomically, LAST, so a torn save
  is detectable by its absence — records per leaf the global shape,
  dtype, partition spec, and per shard the covered index box, byte count,
  and a blake2b content digest.
- **verify**: every shard's bytes are re-digested against the manifest
  BEFORE any weight loads. A truncated or bit-flipped shard names the
  exact leaf and file instead of poisoning the restore halfway through.
- **load**: each leaf is assembled for an arbitrary target sharding.
  When a requested device slice is exactly covered by one saved shard
  (layouts line up — the same-topology resume), the shard file is read
  directly; otherwise the leaf is assembled once from its shard boxes
  and sliced (the N-host -> M-host path). ``last_load_stats`` exposes
  which path ran so tests pin the fast path staying fast.

No jax import at module scope: the manifest/verify half is used by
resume tooling (Saver pointer validation, RecoverHandler fallback) that
must work in jax-free processes; only the sharded-placement load path
imports jax, lazily.
"""

from __future__ import annotations

import hashlib
import json
import os
import re

import numpy as np

from areal_tpu.utils import logging
from areal_tpu.utils.fs import atomic_write, atomic_write_json

logger = logging.getLogger("checkpoint")

MANIFEST_NAME = "manifest.json"
SHARD_DIR = "shards"

#: manifest schema; bump on incompatible layout changes. A manifest
#: written by a NEWER schema refuses to load.
MANIFEST_SCHEMA = 1


class CheckpointCorrupted(RuntimeError):
    """A shard failed digest verification, a shard file is missing or
    truncated, or the manifest itself is torn. The message names the
    exact leaf/file so the postmortem starts at the failure, not at a
    generic load error."""


def _slug(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 etc.

        return np.dtype(getattr(ml_dtypes, name))


def shard_digest(arr: np.ndarray) -> str:
    """blake2b content fingerprint of one shard (dtype and box shape are
    part of the identity, matching the engine's leaf-digest idiom)."""
    arr = np.ascontiguousarray(arr)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(str(tuple(arr.shape)).encode())
    # 1-d uint8 view (not tobytes): hash in place without a byte copy;
    # reshape(-1) first because 0-d arrays refuse cross-itemsize views
    h.update(arr.reshape(-1).view(np.uint8))
    return h.hexdigest()


def _normalize_index(index, shape) -> list[list[int]]:
    """A shard's index (tuple of slices from ``addressable_shards``, or an
    already-normalized box) as ``[[lo, hi], ...]`` per dimension."""
    out = []
    for k, dim in enumerate(shape):
        s = index[k] if k < len(index) else slice(None)
        if isinstance(s, slice):
            lo, hi, step = s.indices(dim)
            if step != 1:
                raise ValueError(f"strided shard index unsupported: {s}")
            out.append([lo, hi])
        else:
            lo, hi = s
            out.append([int(lo), int(hi)])
    return out


def _box_shape(box: list[list[int]]) -> tuple[int, ...]:
    return tuple(hi - lo for lo, hi in box)


def _box_slices(box: list[list[int]]) -> tuple[slice, ...]:
    return tuple(slice(lo, hi) for lo, hi in box)


class CheckpointWriter:
    """Stages one checkpoint: shard files first (each atomic), manifest
    last (atomic) — the manifest IS the commit record, so a crash at any
    point leaves either no manifest (torn save, detected) or a complete,
    verifiable checkpoint."""

    def __init__(self, path: str):
        self.path = path
        self.leaves: dict[str, dict] = {}
        os.makedirs(os.path.join(path, SHARD_DIR), exist_ok=True)

    def add_shard(
        self,
        name: str,
        global_shape,
        dtype: str,
        index,
        data: np.ndarray,
        spec=None,
    ) -> str:
        """Write one shard of leaf ``name`` covering ``index`` (a tuple of
        slices or an ``[[lo, hi], ...]`` box). The low-level entry point —
        multi-host savers (and the multi-host *emulation* in tests) call
        this once per local shard; :meth:`add_leaf` fans out to it."""
        global_shape = tuple(int(d) for d in global_shape)
        box = _normalize_index(index, global_shape)
        data = np.ascontiguousarray(data)
        want = _box_shape(box)
        if data.shape != want:
            # scalar shards can materialize as (1,); same element count is
            # the same bytes
            if data.size != int(np.prod(want, dtype=np.int64)):
                raise ValueError(
                    f"shard data shape {data.shape} does not cover index "
                    f"box {box} of leaf {name!r}"
                )
            data = data.reshape(want)
        entry = self.leaves.setdefault(
            name,
            {
                "shape": list(global_shape),
                "dtype": str(data.dtype) if dtype is None else str(dtype),
                "spec": spec,
                "shards": [],
            },
        )
        k = len(entry["shards"])
        rel = os.path.join(SHARD_DIR, f"{_slug(name)}.{k}.bin")
        flat = data.reshape(-1).view(np.uint8)
        atomic_write(
            os.path.join(self.path, rel),
            lambda f: f.write(memoryview(flat)),
            binary=True,
        )
        entry["shards"].append(
            {
                "file": rel,
                "index": box,
                "nbytes": int(flat.nbytes),
                "digest": shard_digest(data),
            }
        )
        return rel

    def add_leaf(self, name: str, leaf, spec=None) -> None:
        """Write every locally-addressable shard of one (possibly jax,
        possibly plain numpy) leaf. Replicated shards (replica_id != 0)
        are skipped — their bytes are identical to replica 0's."""
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            arr = np.asarray(leaf)
            self.add_shard(
                name,
                arr.shape,
                str(arr.dtype),
                [[0, d] for d in arr.shape],
                arr,
                spec=spec,
            )
            return
        shape = tuple(leaf.shape)
        dtype = str(leaf.dtype)
        seen: set[str] = set()
        # deterministic shard order (shard.index is a tuple of slices —
        # not orderable; its repr is a stable key, replica_id breaks ties)
        for s in sorted(shards, key=lambda s: (str(s.index), s.replica_id)):
            box = _normalize_index(s.index, shape)
            key = str(box)
            if key in seen:
                continue  # a replica of an already-written box
            seen.add(key)
            self.add_shard(
                name, shape, dtype, box, np.asarray(s.data), spec=spec
            )

    def commit(self, extras: dict | None = None) -> dict:
        manifest = {
            "schema_version": MANIFEST_SCHEMA,
            "leaves": self.leaves,
            "extras": extras or {},
        }
        atomic_write_json(os.path.join(self.path, MANIFEST_NAME), manifest)
        return manifest


def save_named(
    path: str, named: dict, *, extras: dict | None = None, specs: dict | None = None
) -> dict:
    """Save a flat ``{dotted-path: leaf}`` mapping as one manifest
    checkpoint. ``specs`` optionally maps leaf names to a json-safe
    partition-spec description (informational — restore re-derives the
    target sharding from ITS mesh, never from the saved one; recording it
    anyway makes a foreign checkpoint self-describing)."""
    w = CheckpointWriter(path)
    for name in sorted(named.keys()):
        w.add_leaf(name, named[name], spec=(specs or {}).get(name))
    return w.commit(extras=extras)


def read_manifest(path: str) -> dict:
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            m = json.load(f)
    except OSError as e:
        raise CheckpointCorrupted(
            f"checkpoint at {path} has no readable {MANIFEST_NAME} ({e}) — "
            "the save never committed"
        ) from e
    except json.JSONDecodeError as e:
        raise CheckpointCorrupted(f"{mpath} is torn ({e})") from e
    schema = int(m.get("schema_version", 0))
    if schema > MANIFEST_SCHEMA:
        raise CheckpointCorrupted(
            f"{mpath} schema {schema} is newer than this build supports "
            f"({MANIFEST_SCHEMA})"
        )
    return m


def is_manifest_checkpoint(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST_NAME))


def _read_shard(path: str, entry: dict, shard: dict) -> np.ndarray:
    fpath = os.path.join(path, shard["file"])
    dtype = _np_dtype(entry["dtype"])
    with open(fpath, "rb") as f:
        raw = f.read()
    if len(raw) != int(shard["nbytes"]):
        raise CheckpointCorrupted(
            f"shard {shard['file']} is truncated: {len(raw)} bytes on disk, "
            f"manifest says {shard['nbytes']}"
        )
    arr = np.frombuffer(raw, dtype=dtype).reshape(
        _box_shape(shard["index"])
    )
    return arr


def verify(path: str, manifest: dict | None = None) -> list[dict]:
    """Digest-check every shard against the manifest; returns failures as
    ``[{leaf, file, reason}, ...]`` (empty = checkpoint verifies). Runs
    BEFORE any weight loads — corruption is refused up front, with the
    failing leaf named, instead of surfacing as garbage weights."""
    if manifest is None:
        manifest = read_manifest(path)
    failures: list[dict] = []
    for name, entry in manifest["leaves"].items():
        for shard in entry["shards"]:
            try:
                arr = _read_shard(path, entry, shard)
            except (OSError, CheckpointCorrupted, ValueError) as e:
                failures.append(
                    {"leaf": name, "file": shard["file"], "reason": str(e)}
                )
                continue
            d = shard_digest(arr)
            if d != shard["digest"]:
                failures.append(
                    {
                        "leaf": name,
                        "file": shard["file"],
                        "reason": (
                            f"digest mismatch: disk {d} != manifest "
                            f"{shard['digest']} (bit flip or torn write)"
                        ),
                    }
                )
    return failures


def verify_or_raise(path: str, manifest: dict | None = None) -> dict:
    """verify(), raising :class:`CheckpointCorrupted` naming the first
    failing leaf (and recording every failure to the flight recorder so
    the postmortem survives whatever the caller does next)."""
    if manifest is None:
        manifest = read_manifest(path)
    failures = verify(path, manifest)
    if failures:
        try:
            from areal_tpu.utils import flight_recorder

            for f in failures:
                flight_recorder.record(
                    "checkpoint", "shard_verify_failed", path=path, **f
                )
        except Exception:  # evidence is best-effort, the refusal is not
            logger.debug("flight record of verify failure failed", exc_info=True)
        first = failures[0]
        raise CheckpointCorrupted(
            f"checkpoint at {path} failed digest verification: leaf "
            f"{first['leaf']!r} ({first['file']}): {first['reason']}"
            + (f" [+{len(failures) - 1} more]" if len(failures) > 1 else "")
        )
    return manifest


#: stats of the most recent load_named call: how many device slices were
#: satisfied by a direct single-shard file read (layouts lined up) vs how
#: many leaves needed gather-and-slice assembly (topology changed)
last_load_stats: dict[str, int] = {}


def _assemble(path: str, entry: dict) -> np.ndarray:
    """Gather-and-slice fallback: materialize one leaf's global array from
    its shard boxes."""
    out = np.empty(tuple(entry["shape"]), dtype=_np_dtype(entry["dtype"]))
    covered = 0
    for shard in entry["shards"]:
        arr = _read_shard(path, entry, shard)
        out[_box_slices(shard["index"])] = arr
        covered += arr.size
    if covered < out.size:
        raise CheckpointCorrupted(
            f"shards cover {covered} of {out.size} elements — the manifest "
            "is incomplete (partial multi-host save?)"
        )
    return out


def load_named(
    path: str,
    *,
    shardings: dict | None = None,
    manifest: dict | None = None,
    verify_digests: bool = True,
) -> tuple[dict, dict]:
    """Load every leaf, re-sharded for THIS process's topology. Returns
    ``(named, extras)``.

    ``shardings`` maps leaf names to target ``jax.sharding.Sharding``s;
    leaves with an entry come back as committed global jax arrays built
    via ``make_array_from_callback`` (each device slice read directly
    from a single shard file when one exactly covers it), everything else
    as plain numpy. Digest verification runs first unless explicitly
    disabled (the caller already verified, e.g. the recover fallback
    scan)."""
    global last_load_stats
    if manifest is None:
        manifest = read_manifest(path)
    if verify_digests:
        verify_or_raise(path, manifest)
    stats = {"direct_shard_reads": 0, "assembled_leaves": 0}
    named: dict = {}
    for name, entry in manifest["leaves"].items():
        sharding = (shardings or {}).get(name)
        if sharding is None:
            only = entry["shards"][0] if len(entry["shards"]) == 1 else None
            if only is not None and _box_shape(only["index"]) == tuple(
                entry["shape"]
            ):
                named[name] = _read_shard(path, entry, only)
                stats["direct_shard_reads"] += 1
            else:
                named[name] = _assemble(path, entry)
                stats["assembled_leaves"] += 1
            continue
        import jax  # lazy: manifest/verify callers may be jax-free

        by_box = {str(_normalize_index(s["index"], entry["shape"])): s
                  for s in entry["shards"]}
        shape = tuple(entry["shape"])
        cache: dict[str, np.ndarray] = {}

        def cb(index, entry=entry, by_box=by_box, shape=shape, cache=cache):
            box = _normalize_index(index, shape)
            hit = by_box.get(str(box))
            if hit is not None:
                stats["direct_shard_reads"] += 1
                return _read_shard(path, entry, hit)
            # layouts differ: assemble the global leaf once, slice per
            # device (the cache keys on the leaf, shared across devices)
            if "g" not in cache:
                cache["g"] = _assemble(path, entry)
                stats["assembled_leaves"] += 1
            return cache["g"][_box_slices(box)]

        named[name] = jax.make_array_from_callback(shape, sharding, cb)
    last_load_stats = stats
    if stats["assembled_leaves"]:
        logger.info(
            "checkpoint %s re-sharded for a different topology: %d leaf(s) "
            "assembled+sliced, %d direct shard read(s)",
            path,
            stats["assembled_leaves"],
            stats["direct_shard_reads"],
        )
    return named, manifest.get("extras", {})


def tree_digest(named: dict) -> str:
    """Order-independent content digest of a whole named tree (each leaf
    materialized to host bytes) — the bit-identity pin for
    cross-topology resume tests: save on mesh A, load on mesh B, equal
    tree_digest means equal parameters."""
    h = hashlib.blake2b(digest_size=16)
    for name in sorted(named.keys()):
        arr = np.ascontiguousarray(np.asarray(named[name]))
        h.update(name.encode())
        h.update(shard_digest(arr).encode())
    return h.hexdigest()


def verify_checkpoint_dir(path: str) -> tuple[bool, str]:
    """Generic resume-time validity probe, format-agnostic: manifest
    checkpoints digest-verify; anything else (HF safetensors dirs) passes
    if the directory exists and holds at least one regular file. Used by
    the Saver's ``latest``-pointer fallback scan."""
    if not os.path.isdir(path):
        return False, f"{path} is not a directory"
    if not is_manifest_checkpoint(path) and os.path.isdir(
        os.path.join(path, SHARD_DIR)
    ):
        # shard files without a manifest: a manifest-format save that
        # crashed before committing — NOT a valid foreign-format checkpoint
        return False, (
            f"{path} has a {SHARD_DIR}/ directory but no {MANIFEST_NAME} — "
            "the save never committed"
        )
    if is_manifest_checkpoint(path):
        try:
            failures = verify(path)
        except CheckpointCorrupted as e:
            return False, str(e)
        if failures:
            f = failures[0]
            return False, (
                f"leaf {f['leaf']!r} ({f['file']}): {f['reason']}"
                + (f" [+{len(failures) - 1} more]" if len(failures) > 1 else "")
            )
        return True, "manifest verified"
    try:
        for root, _, files in os.walk(path):
            if files:
                return True, "non-manifest checkpoint (existence check only)"
    except OSError as e:
        return False, str(e)
    return False, f"{path} is empty"
