"""Rank-0 experiment metrics logger (reference: areal/utils/stats_logger.py:148).

Always writes a ``stats.jsonl`` under the trial dir; optionally mirrors to
tensorboard (if installed) and wandb (if installed + enabled). Pretty-prints
each commit like the reference.
"""

from __future__ import annotations

import json
import os
from typing import Any

from areal_tpu.api.cli_args import StatsLoggerConfig
from areal_tpu.utils import logging

logger = logging.getLogger("StatsLogger")


class StatsLogger:
    def __init__(
        self, config: StatsLoggerConfig, ft_spec=None, rank: int | None = None
    ):
        self.config = config
        self.ft_spec = ft_spec
        if rank is None:
            # multi-host: only the jax.distributed main process logs
            from areal_tpu.parallel import distributed

            rank = distributed.process_index()
        self.rank = rank
        self._jsonl = None
        self._tb = None
        self._wandb = None
        # resume dedup floor: commits at or below it are replays of steps
        # recorded before a crash and are skipped. ARMED ONLY on recovery
        # (load_state_dict, called by RecoverHandler.load): a fresh run
        # that happens to reuse an experiment/trial name must keep logging,
        # not silently suppress every step the old file already has.
        self.last_logged_step = -1
        # highest step found in the existing jsonl at reopen (scan also
        # truncates a torn tail regardless of recovery)
        self._on_disk_step = -1
        self._dedup_armed = False  # set by load_state_dict (recovery only)
        self._warned_stale_logs = False
        mcfg = getattr(config, "metrics", None)
        if mcfg is not None and mcfg.enabled:
            from areal_tpu.utils import metrics as _metrics

            _metrics.DEFAULT_REGISTRY.set_max_label_values(
                mcfg.max_label_values
            )
        if rank == 0:
            self._init_backends()

    def log_dir(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "logs",
        )

    def _init_backends(self):
        os.makedirs(self.log_dir(), exist_ok=True)
        path = os.path.join(self.log_dir(), "stats.jsonl")
        self._on_disk_step = self._repair_and_scan(path)
        self._jsonl = open(path, "a")
        if self.config.tensorboard.path:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=self.config.tensorboard.path)
            except Exception:
                logger.warning("tensorboard unavailable; skipping")
        if self.config.wandb.mode != "disabled":
            try:
                import wandb

                wcfg = self.config.wandb
                if wcfg.wandb_api_key:
                    os.environ.setdefault("WANDB_API_KEY", wcfg.wandb_api_key)
                if wcfg.wandb_base_url:
                    os.environ.setdefault("WANDB_BASE_URL", wcfg.wandb_base_url)
                name = wcfg.name or self.config.trial_name
                wandb.init(
                    mode=wcfg.mode,
                    project=wcfg.project or self.config.experiment_name,
                    entity=wcfg.entity,
                    name=name + (wcfg.id_suffix or ""),
                    job_type=wcfg.job_type,
                    group=wcfg.group,
                    notes=wcfg.notes,
                    tags=list(wcfg.tags) if wcfg.tags else None,
                    config=wcfg.config,
                )
                self._wandb = wandb
            except Exception:
                logger.warning("wandb unavailable; skipping")

    def _repair_and_scan(self, path: str) -> int:
        """Reopen protocol for crash-consistent append: scan the existing
        jsonl for the highest recorded global_step, and truncate a torn
        trailing line (a crash mid-``write``) so the file stays valid
        jsonl. Returns the last recorded step (-1 for a fresh file)."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return -1
        last_step = -1
        valid_end = 0
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break  # torn tail: crash mid-write
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn/garbled line: everything after is suspect
                if isinstance(rec, dict) and "global_step" in rec:
                    last_step = max(last_step, int(rec["global_step"]))
                valid_end += len(line)
        if valid_end < size:
            logger.warning(
                "truncating %d byte(s) of torn tail from %s (crash "
                "mid-write)",
                size - valid_end,
                path,
            )
            with open(path, "r+b") as f:
                f.truncate(valid_end)
        return last_step

    def commit(
        self,
        epoch: int,
        step: int,
        global_step: int,
        stats: dict[str, float] | list[dict[str, float]],
    ):
        if self.rank != 0:
            return
        if (
            not self._dedup_armed
            and self._on_disk_step >= 0
            and not self._warned_stale_logs
        ):
            # a fresh (non-recovery) run appending over another run's
            # jsonl: logging proceeds, but if THIS run later crashes and
            # resumes, the dedup scan cannot tell the old run's records
            # from this one's and will skip steps up to the old maximum —
            # start fresh trials in a clean trial dir
            self._warned_stale_logs = True
            logger.warning(
                "stats.jsonl already holds records up to global step %d "
                "from a previous run of this trial name; a future resume "
                "of THIS run would treat them as already-logged. Prefer a "
                "clean trial dir for fresh runs.",
                self._on_disk_step,
            )
        if global_step <= self.last_logged_step:
            logger.info(
                "skipping stats commit for global step %d: already "
                "recorded before restart (last logged %d)",
                global_step,
                self.last_logged_step,
            )
            return
        if isinstance(stats, list):
            merged: dict[str, Any] = {}
            for s in stats:
                merged.update(s)
            stats = merged
        mcfg = getattr(self.config, "metrics", None)
        if (
            mcfg is not None
            and mcfg.enabled
            and mcfg.stats_logger_prefix
        ):
            # trainer-side periodic export of the unified metrics
            # registry: every commit row carries the registry's current
            # scalars (counters/gauges cumulative, histograms as
            # count/sum/p50/p95/p99), so stats.jsonl is the one place
            # metrics land even without a Prometheus scraper. Explicit
            # per-step stats win on key collision.
            from areal_tpu.utils import metrics as _metrics

            stats = {
                **_metrics.DEFAULT_REGISTRY.export_scalars(
                    prefix=mcfg.stats_logger_prefix
                ),
                **stats,
            }
        logger.info(
            "Epoch %d step %d (global %d): %s",
            epoch,
            step,
            global_step,
            " ".join(f"{k}={v:.4g}" for k, v in sorted(stats.items())),
        )
        rec = {"epoch": epoch, "step": step, "global_step": global_step, **stats}
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        self.last_logged_step = max(self.last_logged_step, global_step)
        if self._tb is not None:
            for k, v in stats.items():
                self._tb.add_scalar(k, v, global_step)
        if self._wandb is not None:
            self._wandb.log(stats, step=global_step)

    def state_dict(self) -> dict:
        return {
            "last_logged_step": max(self.last_logged_step, self._on_disk_step)
        }

    def load_state_dict(self, s: dict):
        # called on RECOVERY only (RecoverHandler.load): arm the dedup
        # floor from whichever is further along — the on-disk scan (jsonl
        # survived) or the RunState value (jsonl on ephemeral disk lost)
        self._dedup_armed = True
        self.last_logged_step = max(
            self.last_logged_step,
            self._on_disk_step,
            int(s.get("last_logged_step", -1)),
        )

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
