"""Rank-0 experiment metrics logger (reference: areal/utils/stats_logger.py:148).

Always writes a ``stats.jsonl`` under the trial dir; optionally mirrors to
tensorboard (if installed) and wandb (if installed + enabled). Pretty-prints
each commit like the reference.
"""

from __future__ import annotations

import json
import os
from typing import Any

from areal_tpu.api.cli_args import StatsLoggerConfig
from areal_tpu.utils import logging

logger = logging.getLogger("StatsLogger")


class StatsLogger:
    def __init__(
        self, config: StatsLoggerConfig, ft_spec=None, rank: int | None = None
    ):
        self.config = config
        self.ft_spec = ft_spec
        if rank is None:
            # multi-host: only the jax.distributed main process logs
            from areal_tpu.parallel import distributed

            rank = distributed.process_index()
        self.rank = rank
        self._jsonl = None
        self._tb = None
        self._wandb = None
        if rank == 0:
            self._init_backends()

    def log_dir(self) -> str:
        return os.path.join(
            self.config.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "logs",
        )

    def _init_backends(self):
        os.makedirs(self.log_dir(), exist_ok=True)
        self._jsonl = open(os.path.join(self.log_dir(), "stats.jsonl"), "a")
        if self.config.tensorboard.path:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=self.config.tensorboard.path)
            except Exception:
                logger.warning("tensorboard unavailable; skipping")
        if self.config.wandb.mode != "disabled":
            try:
                import wandb

                wcfg = self.config.wandb
                if wcfg.wandb_api_key:
                    os.environ.setdefault("WANDB_API_KEY", wcfg.wandb_api_key)
                if wcfg.wandb_base_url:
                    os.environ.setdefault("WANDB_BASE_URL", wcfg.wandb_base_url)
                name = wcfg.name or self.config.trial_name
                wandb.init(
                    mode=wcfg.mode,
                    project=wcfg.project or self.config.experiment_name,
                    entity=wcfg.entity,
                    name=name + (wcfg.id_suffix or ""),
                    job_type=wcfg.job_type,
                    group=wcfg.group,
                    notes=wcfg.notes,
                    tags=list(wcfg.tags) if wcfg.tags else None,
                    config=wcfg.config,
                )
                self._wandb = wandb
            except Exception:
                logger.warning("wandb unavailable; skipping")

    def commit(
        self,
        epoch: int,
        step: int,
        global_step: int,
        stats: dict[str, float] | list[dict[str, float]],
    ):
        if self.rank != 0:
            return
        if isinstance(stats, list):
            merged: dict[str, Any] = {}
            for s in stats:
                merged.update(s)
            stats = merged
        logger.info(
            "Epoch %d step %d (global %d): %s",
            epoch,
            step,
            global_step,
            " ".join(f"{k}={v:.4g}" for k, v in sorted(stats.items())),
        )
        rec = {"epoch": epoch, "step": step, "global_step": global_step, **stats}
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._tb is not None:
            for k, v in stats.items():
                self._tb.add_scalar(k, v, global_step)
        if self._wandb is not None:
            self._wandb.log(stats, step=global_step)

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
