"""Image transport helpers (reference: areal/utils/image.py base64 transport).

Images travel client -> server as base64-encoded raw float arrays (shape
header + bytes) — no PIL/JPEG dependency in the TPU image, and the encoder
consumes float pixel grids anyway. The trainer keeps the decoded arrays in
the batch as ``pixel_values``.
"""

from __future__ import annotations

import base64
import io

import numpy as np


def encode_image(arr: np.ndarray) -> str:
    """float32 [H, W, 3] (values in [0, 1]) -> base64 string."""
    arr = np.ascontiguousarray(np.asarray(arr, np.float32))
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_image(s: str) -> np.ndarray:
    raw = base64.b64decode(s.encode("ascii"))
    return np.load(io.BytesIO(raw), allow_pickle=False)
