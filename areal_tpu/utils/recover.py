"""Step-level recovery: persist everything needed to resume a trial
(reference: areal/utils/recover.py:385 — RecoverHandler/RecoverInfo).

``RecoverHandler.dump`` writes, per checkpointed step:
- the engine checkpoint (weights + optimizer, orbax format),
- the dataloader position (StatefulDataLoader.state_dict),
- Saver/Evaluator timer states,
- a ``RecoverInfo`` json: last StepInfo + a config hash (refusing to resume
  onto a changed config).

``check_if_recover`` mirrors the reference's AREAL_RECOVER_RUN env protocol:
launchers relaunch failed trials with the env set, and the entry script calls
``RecoverHandler.load`` to fast-forward.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
from dataclasses import dataclass

from areal_tpu.api.cli_args import RecoverConfig, to_dict
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo
from areal_tpu.utils import logging
from areal_tpu.utils.saver import FreqTimer

logger = logging.getLogger("recover")

RECOVER_ENV = "AREAL_RECOVER_RUN"


class RecoverStateCorrupted(RuntimeError):
    """The on-disk recover state is unreadable (truncated json, partial
    pickle, missing checkpoint). Raised instead of the raw decode error so
    the launcher refuses to resume with a clear message rather than
    crashing opaquely — delete the recover dir to start fresh."""


def _atomic_write(path: str, write_fn, binary: bool = False) -> None:
    """Write via tmp-file + rename so readers never see a partial file."""
    tmp = path + ".tmp"
    with open(tmp, "wb" if binary else "w") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def config_hash(cfg) -> str:
    try:
        blob = json.dumps(to_dict(cfg), sort_keys=True, default=str)
    except Exception:
        blob = repr(cfg)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass
class RecoverInfo:
    last_step_info: StepInfo
    config_hash: str = ""

    def to_json(self) -> dict:
        return {
            "last_step_info": dataclasses.asdict(self.last_step_info),
            "config_hash": self.config_hash,
        }

    @classmethod
    def from_json(cls, d: dict) -> "RecoverInfo":
        return cls(
            last_step_info=StepInfo(**d["last_step_info"]),
            config_hash=d.get("config_hash", ""),
        )


def check_if_recover(config: RecoverConfig, run_id: int | None = None) -> bool:
    """Is this process a recovery run? (reference recover.py:373)"""
    if config.mode == "disabled":
        return False
    if config.mode == "resume":
        return True
    if config.mode in ("auto", "fault"):
        env = os.environ.get(RECOVER_ENV, "0")
        if env not in ("0", ""):
            return True
        if run_id is not None and run_id > 0:
            return True
        # auto also recovers when a checkpoint exists
        return config.mode == "auto"
    return False


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )

    def recover_root(self, fileroot: str, experiment_name: str, trial_name: str) -> str:
        return os.path.join(fileroot, experiment_name, trial_name, "recover")

    def dump(
        self,
        engine,
        step: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        *,
        fileroot: str,
        experiment_name: str,
        trial_name: str,
        tokenizer=None,
        config=None,
        force: bool = False,
    ) -> str | None:
        if self.config.mode == "disabled":
            return None
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return None
        root = self.recover_root(fileroot, experiment_name, trial_name)
        os.makedirs(root, exist_ok=True)
        engine.save(
            SaveLoadMeta(
                path=os.path.join(root, "engine"),
                weight_format="orbax",
                with_optim=True,
                tokenizer=tokenizer,
            )
        )
        state = {
            "dataloader": dataloader.state_dict() if dataloader is not None else None,
            "saver": saver.state_dict() if saver is not None else None,
            "evaluator": evaluator.state_dict() if evaluator is not None else None,
        }
        # write-then-rename: a crash mid-dump must leave either the previous
        # consistent state or none, never a truncated file that a recovery
        # run would choke on. recover_info.json goes LAST — its presence is
        # the commit marker for the whole dump.
        _atomic_write(
            os.path.join(root, "loop_state.pkl"),
            lambda f: pickle.dump(state, f),
            binary=True,
        )
        info = RecoverInfo(
            last_step_info=step,
            config_hash=config_hash(config) if config is not None else "",
        )
        _atomic_write(
            os.path.join(root, "recover_info.json"),
            lambda f: json.dump(info.to_json(), f),
        )
        self.timer.reset()
        logger.info("recover state dumped at %s (step %d)", root, step.global_step)
        return root

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        *,
        fileroot: str,
        experiment_name: str,
        trial_name: str,
        config=None,
    ) -> RecoverInfo | None:
        root = self.recover_root(fileroot, experiment_name, trial_name)
        info_path = os.path.join(root, "recover_info.json")
        if not os.path.isfile(info_path):
            return None
        try:
            with open(info_path) as f:
                info = RecoverInfo.from_json(json.load(f))
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: {info_path} is corrupted ({e}); "
                f"delete {root} to start the trial fresh"
            ) from e
        if config is not None and info.config_hash:
            h = config_hash(config)
            if h != info.config_hash:
                raise RuntimeError(
                    f"refusing to recover: config hash {h} != saved "
                    f"{info.config_hash} (the trial config changed)"
                )
        try:
            engine.load(
                SaveLoadMeta(
                    path=os.path.join(root, "engine"),
                    weight_format="orbax",
                    with_optim=True,
                )
            )
        except Exception as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: engine checkpoint under {root} is "
                f"partial or corrupted ({e}); delete {root} to start fresh"
            ) from e
        try:
            with open(os.path.join(root, "loop_state.pkl"), "rb") as f:
                state = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError) as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: {root}/loop_state.pkl is corrupted "
                f"({e}); delete {root} to start fresh"
            ) from e
        if dataloader is not None and state.get("dataloader") is not None:
            dataloader.load_state_dict(state["dataloader"])
        if saver is not None and state.get("saver") is not None:
            saver.load_state_dict(state["saver"])
        if evaluator is not None and state.get("evaluator") is not None:
            evaluator.load_state_dict(state["evaluator"])
        logger.info(
            "recovered from %s at global step %d",
            root,
            info.last_step_info.global_step,
        )
        return info
