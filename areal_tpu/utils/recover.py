"""Step-level recovery: persist everything needed to resume a trial
step-exactly (reference: areal/utils/recover.py:385 — RecoverHandler),
plus the preemption plane: a SIGTERM guard that turns a kill notice into
pause -> rollout drain -> checkpoint within a grace budget.

``RecoverHandler.dump`` writes, per checkpointed step:

- the engine checkpoint (weights + optimizer; by default the re-shardable
  digest-manifest format of utils/checkpoint.py, so a replacement trainer
  with a DIFFERENT host count or mesh shape resumes the same run —
  ``RecoverConfig.checkpoint_format="orbax"`` keeps the same-topology
  format),
- a ``run_state.json`` copy staged INSIDE the dump directory (fallback
  restores read the loop state of the dump they actually land on),
- a ``loop_state.pkl``: dataloader cursor (seeded shuffle position),
  Saver/Evaluator timer states, python/numpy PRNG states, stats-logger
  state, and any rollouts drained by a graceful shutdown,
- a versioned :class:`RunState` json: last StepInfo, weight version,
  staleness counters, last stats-logger step, last saver checkpoint path,
  and a config hash (refusing to resume onto a changed config).

Crash consistency: each dump is staged into its own
``dump_globalstep{N}`` directory (engine checkpoint + loop_state.pkl), and
only then is the root ``recover_info.json`` marker flipped — atomically,
via write-then-rename — to reference it; the previous dump directory is
deleted only after the new marker is committed. A crash at ANY point
(including the ``mid-checkpoint`` ``AREAL_CRASH_AT`` barrier between the
staging writes and the marker flip) therefore leaves the previous dump
fully intact and referenced, or the new one committed — never a torn mix
of old marker and new state. ``RecoverConfig.keep_dumps`` of the newest
committed dumps are retained (default 2): resume verifies the committed
dump's shard digests BEFORE any weight loads, and a bit-flipped or
truncated shard falls back to the newest retained dump that verifies
instead of stranding the trial. The price is up to ``keep_dumps`` engine
checkpoints on disk, plus one transiently during a dump.

``check_if_recover`` mirrors the reference's AREAL_RECOVER_RUN env protocol:
launchers relaunch failed trials with the env set, and the entry script calls
``RecoverHandler.load`` to fast-forward.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import random
import re
import shutil
import signal
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from areal_tpu.api.cli_args import RecoverConfig, to_dict
from areal_tpu.api.io_struct import SaveLoadMeta, StepInfo, TimedResult
from areal_tpu.utils import checkpoint as ckpt_fmt
from areal_tpu.utils import logging
from areal_tpu.utils.chaos import crash_point
from areal_tpu.utils.fs import atomic_write
from areal_tpu.utils.saver import FreqTimer

logger = logging.getLogger("recover")

RECOVER_ENV = "AREAL_RECOVER_RUN"

#: RunState schema; bump when the json layout changes incompatibly. A state
#: written by a NEWER schema refuses to load (older fields are defaulted).
RUN_STATE_SCHEMA = 1

#: exit code a trainer uses after a successful graceful-preemption
#: checkpoint; the launcher treats it like any failure (relaunch + resume)
PREEMPTION_EXIT_CODE = 42

# compat alias: the original helper moved to utils/fs.atomic_write so the
# saver (retention pointer) and future checkpoint writers share it
_atomic_write = atomic_write

#: staged dump directory naming; the retention/fallback scans parse the
#: global step (and the same-step re-dump suffix) back out of it to order
#: candidates newest-first
_DUMP_DIR_RE = re.compile(r"^dump_globalstep(\d+)(?:\.(\d+))?$")


def _dump_sort_key(name: str) -> tuple[int, int]:
    m = _DUMP_DIR_RE.match(name)
    assert m, name
    return (int(m.group(1)), int(m.group(2) or 0))


class RecoverStateCorrupted(RuntimeError):
    """The on-disk recover state is unreadable (truncated json, partial
    pickle, missing checkpoint). Raised instead of the raw decode error so
    the launcher refuses to resume with a clear message rather than
    crashing opaquely — delete the recover dir to start fresh."""


def config_hash(cfg) -> str:
    try:
        blob = json.dumps(to_dict(cfg), sort_keys=True, default=str)
    except Exception:
        blob = repr(cfg)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass
class RunState:
    """Versioned, crash-consistent snapshot of the trainer loop's control
    state — everything a restarted trainer needs (besides the engine
    checkpoint itself) to continue the run step-exactly."""

    last_step_info: StepInfo
    config_hash: str = ""
    schema_version: int = RUN_STATE_SCHEMA
    #: inference-plane weight version at dump time; resume reconciliation
    #: re-pushes weights to any server stuck below it
    weight_version: int = 0
    #: StalenessManager counters (running rebalances to rejected on load)
    rollout_stat: dict = field(default_factory=dict)
    #: last global step the stats logger committed (resume dedup cross-check)
    stats_logger_step: int = -1
    #: last Saver checkpoint path — retention GC must never delete it
    last_save_path: str | None = None
    #: dump directory (relative to the recover root) this marker commits;
    #: None means the pre-RunState flat layout (engine/ + loop_state.pkl
    #: directly under the root)
    dump_dir: str | None = None

    def to_json(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "last_step_info": dataclasses.asdict(self.last_step_info),
            "config_hash": self.config_hash,
            "weight_version": self.weight_version,
            "rollout_stat": self.rollout_stat,
            "stats_logger_step": self.stats_logger_step,
            "last_save_path": self.last_save_path,
            "dump_dir": self.dump_dir,
        }

    @classmethod
    def from_json(cls, d: dict) -> "RunState":
        schema = int(d.get("schema_version", 1))
        if schema > RUN_STATE_SCHEMA:
            raise RecoverStateCorrupted(
                f"run state schema {schema} is newer than this build "
                f"supports ({RUN_STATE_SCHEMA}); upgrade the trainer or "
                "delete the recover dir to start fresh"
            )
        return cls(
            last_step_info=StepInfo(**d["last_step_info"]),
            config_hash=d.get("config_hash", ""),
            schema_version=schema,
            weight_version=int(d.get("weight_version", 0)),
            rollout_stat=d.get("rollout_stat", {}) or {},
            stats_logger_step=int(d.get("stats_logger_step", -1)),
            last_save_path=d.get("last_save_path"),
            dump_dir=d.get("dump_dir"),
        )


#: historical name — pre-RunState recover_info.json files load through the
#: same (defaults-tolerant) from_json
RecoverInfo = RunState


def check_if_recover(config: RecoverConfig, run_id: int | None = None) -> bool:
    """Is this process a recovery run? (reference recover.py:373)"""
    if config.mode == "disabled":
        return False
    if config.mode == "resume":
        return True
    if config.mode in ("auto", "fault"):
        env = os.environ.get(RECOVER_ENV, "0")
        if env not in ("0", ""):
            return True
        if run_id is not None and run_id > 0:
            return True
        # auto also recovers when a checkpoint exists
        return config.mode == "auto"
    return False


def _rollout_snapshot(rollout):
    """(weight_version, staleness_manager, executor) from whatever rollout
    object the trainer holds: a RemoteInfEngine (has .executor), a bare
    WorkflowExecutor, or None."""
    if rollout is None:
        return None, None, None
    version = rollout.get_version() if hasattr(rollout, "get_version") else None
    executor = getattr(rollout, "executor", rollout)
    manager = getattr(executor, "staleness_manager", None)
    if not hasattr(executor, "readmit_drained"):
        executor = None
    return version, manager, executor


def _counters_as_if_crashed_now(staleness, executor) -> dict:
    """Staleness counters to persist: the snapshot must describe the world
    the RESUMED process will actually see. Completed-but-unconsumed
    trajectories sitting in the output queue / result cache are counted
    ``accepted`` by the live manager, but unless they ride the dump as
    ``drained`` they die with the process — restoring them as accepted
    would permanently shrink the staleness capacity
    (``(max_staleness+v+1)*bs - (accepted+running)``) by phantoms and can
    deadlock rollout submission. Move the not-persisted ones
    accepted -> rejected in the PERSISTED copy only (the live manager is
    untouched; clamped against racing completions)."""
    if staleness is None:
        return {}
    d = staleness.state_dict()
    if executor is None:
        return d
    # this adjustment applies on the graceful path too: drain() emptied the
    # queues of everything that IS persisted (the drained list), so any
    # queue content observed now is a straggler that finished after the
    # drain deadline — counted accepted by the live manager but absent from
    # the dump, i.e. lost to the restart like any other unconsumed result
    unconsumed = executor.output_queue.qsize() + len(executor.result_cache)
    lost = min(unconsumed, d.get("accepted", 0))
    d["accepted"] = d.get("accepted", 0) - lost
    d["rejected"] = d.get("rejected", 0) + lost
    return d


class PreemptionGuard:
    """Cooperative SIGTERM/preemption-notice handler.

    ``install()`` registers signal handlers (main thread only — Python
    restriction) that merely set a flag and start the grace clock; the
    training loop polls :meth:`should_stop` once per step and runs the
    graceful path (pause -> drain -> checkpoint -> exit
    ``PREEMPTION_EXIT_CODE``) itself, so the checkpoint is written by
    ordinary code, not from a signal context. ``trigger()`` is callable
    directly — tests and cloud preemption-notice pollers (GCE metadata,
    k8s preStop) use it instead of a real signal.
    """

    def __init__(
        self,
        grace_period_seconds: float = 30.0,
        signals: tuple = (signal.SIGTERM,),
        clock=time.monotonic,
    ):
        self.grace_period_seconds = grace_period_seconds
        self._signals = signals
        self._clock = clock
        self._flag = threading.Event()
        self._deadline: float | None = None
        self._received: int | None = None
        self._prev_handlers: dict = {}

    def install(self) -> "PreemptionGuard":
        for s in self._signals:
            self._prev_handlers[s] = signal.signal(s, self._handle)
        return self

    def uninstall(self) -> None:
        for s, h in self._prev_handlers.items():
            signal.signal(s, h)
        self._prev_handlers.clear()

    def _handle(self, signum, frame):
        # no logging here: the handler runs between arbitrary bytecodes of
        # the main thread, and the logging stack's buffered IO is not
        # reentrant — a SIGTERM landing mid-log-write would raise
        # RuntimeError('reentrant call') INTO the training loop, crashing
        # it without the drain+checkpoint this guard exists to run. A raw
        # os.write is a single syscall and async-signal-safe.
        self._received = signum
        self.trigger()
        try:
            os.write(
                2,
                (
                    f"PreemptionGuard: signal {signum} received; draining "
                    f"and checkpointing within {self.grace_period_seconds:.0f}s\n"
                ).encode(),
            )
        except OSError:
            pass

    def trigger(self) -> None:
        """Arm the stop flag and start the grace clock (idempotent)."""
        if not self._flag.is_set():
            self._deadline = self._clock() + self.grace_period_seconds
            self._flag.set()

    def should_stop(self) -> bool:
        return self._flag.is_set()

    def remaining(self) -> float:
        """Seconds left of the grace budget (inf when not triggered)."""
        if self._deadline is None:
            return float("inf")
        return max(0.0, self._deadline - self._clock())


class RecoverHandler:
    def __init__(self, config: RecoverConfig, ft_spec=None):
        self.config = config
        self.ft_spec = ft_spec
        self.timer = FreqTimer(
            config.freq_epochs, config.freq_steps, config.freq_secs
        )

    def recover_root(self, fileroot: str, experiment_name: str, trial_name: str) -> str:
        return os.path.join(fileroot, experiment_name, trial_name, "recover")

    @staticmethod
    def _read_marker(root: str) -> dict:
        """Best-effort read of the root commit marker; {} when missing or
        torn (load() is the strict reader — it refuses torn markers)."""
        try:
            with open(os.path.join(root, "recover_info.json")) as f:
                d = json.load(f)
            return d if isinstance(d, dict) else {}
        except (OSError, json.JSONDecodeError):
            return {}

    def _committed_dump_name(self, root: str) -> str | None:
        """Dump dir the current marker references (None when unreadable)."""
        return self._read_marker(root).get("dump_dir")

    def dump(
        self,
        engine,
        step: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        *,
        fileroot: str,
        experiment_name: str,
        trial_name: str,
        tokenizer=None,
        config=None,
        force: bool = False,
        rollout=None,
        drained: list[TimedResult] | None = None,
    ) -> str | None:
        if self.config.mode == "disabled":
            return None
        last = self.ft_spec.is_epoch_last_step(step.epoch_step) if self.ft_spec else False
        if not force and not self.timer.should_fire(step, last):
            return None
        root = self.recover_root(fileroot, experiment_name, trial_name)
        # stage into a per-step directory; the root marker flips to it LAST.
        # Until then the previous dump stays intact and referenced, so a
        # crash anywhere in here resumes from the previous consistent state.
        # A re-dump of the SAME step (graceful shutdown right after a
        # periodic dump) must not restage into the directory the committed
        # marker references — that would delete the only consistent state
        # on disk — so it picks a distinct suffixed name instead.
        committed = self._committed_dump_name(root)
        base = f"dump_globalstep{step.global_step}"
        dump_name, k = base, 0
        while dump_name == committed:
            k += 1
            dump_name = f"{base}.{k}"
        dump_root = os.path.join(root, dump_name)
        if os.path.isdir(dump_root):
            # a torn staging attempt from a crashed dump at this same step;
            # the marker never committed it (checked above) — restart it
            shutil.rmtree(dump_root, ignore_errors=True)
        os.makedirs(dump_root, exist_ok=True)
        engine.save(
            SaveLoadMeta(
                path=os.path.join(dump_root, "engine"),
                weight_format=getattr(
                    self.config, "checkpoint_format", "sharded"
                ),
                with_optim=True,
                tokenizer=tokenizer,
            )
        )
        weight_version, staleness, rollout_executor = _rollout_snapshot(rollout)
        state = {
            "dataloader": dataloader.state_dict() if dataloader is not None else None,
            "saver": saver.state_dict() if saver is not None else None,
            "evaluator": evaluator.state_dict() if evaluator is not None else None,
            "stats_logger": (
                stats_logger.state_dict()
                if stats_logger is not None and hasattr(stats_logger, "state_dict")
                else None
            ),
            # host PRNG state: the executor's batch shuffle and any
            # workflow-level sampling draw from these; step-exact resume
            # needs the same stream
            "prng": {
                "python": random.getstate(),
                "numpy": np.random.get_state(),
            },
            # rollouts completed-but-unconsumed at a graceful shutdown;
            # resume re-admits or discards them by staleness
            "drained": [(r.t, r.data) for r in (drained or [])],
        }
        atomic_write(
            os.path.join(dump_root, "loop_state.pkl"),
            lambda f: pickle.dump(state, f),
            binary=True,
        )
        info = RunState(
            last_step_info=step,
            config_hash=config_hash(config) if config is not None else "",
            weight_version=weight_version if weight_version is not None else 0,
            rollout_stat=_counters_as_if_crashed_now(staleness, rollout_executor),
            stats_logger_step=(
                stats_logger.last_logged_step
                if stats_logger is not None
                and hasattr(stats_logger, "last_logged_step")
                else -1
            ),
            last_save_path=getattr(saver, "last_save_path", None),
            dump_dir=dump_name,
        )
        # every dump carries its own RunState copy: when the corruption
        # fallback lands on a RETAINED (non-committed) dump, the loop
        # control state must come from that dump's step, not the newer
        # marker's — staged before the barrier, like the rest of the dump
        atomic_write(
            os.path.join(dump_root, "run_state.json"),
            lambda f: json.dump(info.to_json(), f),
        )
        # deterministic kill barrier between the staged state and the commit
        # marker: a crash here must resume from the PREVIOUS dump
        crash_point("mid-checkpoint")
        # the commit point for the whole dump: write-then-rename, LAST
        atomic_write(
            os.path.join(root, "recover_info.json"),
            lambda f: json.dump(info.to_json(), f),
        )
        # only now is the previous dump unreferenced and safe to GC. The
        # newest keep_dumps dumps survive (the current one is by
        # construction the newest) so the digest-verifying restore has a
        # previous consistent state to fall back to; legacy flat-layout
        # files, superseded by the marker, are always removed.
        keep_n = max(int(getattr(self.config, "keep_dumps", 1)), 1)
        dumps = sorted(
            (n for n in os.listdir(root) if _DUMP_DIR_RE.match(n)),
            key=_dump_sort_key,
        )
        survivors = set(dumps[-keep_n:]) | {dump_name}
        for name in os.listdir(root):
            if _DUMP_DIR_RE.match(name) and name not in survivors:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            elif name == "engine":
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            elif name == "loop_state.pkl":
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass
        self.timer.reset()
        logger.info(
            "recover state dumped at %s (step %d)", dump_root, step.global_step
        )
        return dump_root

    @staticmethod
    def _dump_run_state(state_root: str) -> RunState | None:
        """The RunState a dump staged for itself (None when missing or
        torn — pre-fallback-era dumps have no copy)."""
        try:
            with open(os.path.join(state_root, "run_state.json")) as f:
                return RunState.from_json(json.load(f))
        except (OSError, json.JSONDecodeError, KeyError, TypeError, ValueError):
            return None

    def _verify_dump(self, state_root: str) -> str | None:
        """Why this dump cannot be resumed from (None = it verifies).
        Digest verification only applies to manifest-format engine
        checkpoints; other formats get a structural existence check."""
        if not os.path.isfile(os.path.join(state_root, "loop_state.pkl")):
            return "loop_state.pkl missing"
        engine_dir = os.path.join(state_root, "engine")
        if not os.path.isdir(engine_dir):
            return "engine checkpoint missing"
        if getattr(self.config, "verify_digests", True) and (
            ckpt_fmt.is_manifest_checkpoint(engine_dir)
        ):
            try:
                ckpt_fmt.verify_or_raise(engine_dir)
            except ckpt_fmt.CheckpointCorrupted as e:
                return str(e)
        return None

    def _select_dump(self, root: str, info: RunState) -> tuple[str, RunState]:
        """The dump to resume from: the committed one when it verifies,
        else the newest retained dump that does (with ITS staged RunState,
        so the loop rewinds consistently with the older weights). Raises
        :class:`RecoverStateCorrupted` when nothing on disk verifies."""
        if not info.dump_dir:
            return root, info  # legacy flat layout: nothing to scan
        committed_root = os.path.join(root, info.dump_dir)
        reason = self._verify_dump(committed_root)
        if reason is None:
            return committed_root, info
        logger.error(
            "recover: committed dump %s FAILS verification (%s); scanning "
            "retained dumps for a fallback",
            committed_root,
            reason,
        )
        failures = [f"{info.dump_dir}: {reason}"]
        others = sorted(
            (
                n
                for n in os.listdir(root)
                if _DUMP_DIR_RE.match(n) and n != info.dump_dir
            ),
            key=_dump_sort_key,
            reverse=True,
        )
        for name in others:
            state_root = os.path.join(root, name)
            reason = self._verify_dump(state_root)
            if reason is not None:
                failures.append(f"{name}: {reason}")
                continue
            fb_info = self._dump_run_state(state_root)
            if fb_info is None:
                failures.append(f"{name}: verifies but has no run_state.json")
                continue
            logger.error(
                "recover: falling back to retained dump %s (step %d, "
                "rewinding from committed step %d)",
                state_root,
                fb_info.last_step_info.global_step,
                info.last_step_info.global_step,
            )
            return state_root, fb_info
        raise RecoverStateCorrupted(
            "refusing to resume: no retained recover dump verifies — "
            + "; ".join(failures)
            + f"; delete {root} to start the trial fresh"
        )

    def load(
        self,
        engine,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        *,
        fileroot: str,
        experiment_name: str,
        trial_name: str,
        config=None,
        rollout=None,
    ) -> RunState | None:
        root = self.recover_root(fileroot, experiment_name, trial_name)
        info_path = os.path.join(root, "recover_info.json")
        if not os.path.isfile(info_path):
            return None
        try:
            with open(info_path) as f:
                info = RunState.from_json(json.load(f))
        except RecoverStateCorrupted:
            raise
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: {info_path} is corrupted ({e}); "
                f"delete {root} to start the trial fresh"
            ) from e
        if config is not None and info.config_hash:
            h = config_hash(config)
            if h != info.config_hash:
                raise RuntimeError(
                    f"refusing to recover: config hash {h} != saved "
                    f"{info.config_hash} (the trial config changed)"
                )
        # the marker names the committed dump dir; legacy flat-layout
        # markers (no dump_dir) read straight from the root. With digest
        # verification on, a committed dump whose shards fail verification
        # does NOT strand the trial: the scan falls back to the newest
        # retained dump that verifies (reading the loop state from THAT
        # dump's staged run_state.json), before any weight loads.
        state_root, info = self._select_dump(root, info)
        try:
            engine_dir = os.path.join(state_root, "engine")
            engine.load(
                SaveLoadMeta(
                    path=engine_dir,
                    weight_format=(
                        "sharded"
                        if ckpt_fmt.is_manifest_checkpoint(engine_dir)
                        else "orbax"
                    ),
                    with_optim=True,
                )
            )
        except Exception as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: engine checkpoint under {state_root} "
                f"is partial or corrupted ({e}); delete {root} to start fresh"
            ) from e
        try:
            with open(os.path.join(state_root, "loop_state.pkl"), "rb") as f:
                state = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError) as e:
            raise RecoverStateCorrupted(
                f"refusing to resume: {state_root}/loop_state.pkl is "
                f"corrupted ({e}); delete {root} to start fresh"
            ) from e
        if dataloader is not None and state.get("dataloader") is not None:
            dataloader.load_state_dict(state["dataloader"])
        if saver is not None and state.get("saver") is not None:
            saver.load_state_dict(state["saver"])
        if evaluator is not None and state.get("evaluator") is not None:
            evaluator.load_state_dict(state["evaluator"])
        if stats_logger is not None and hasattr(stats_logger, "load_state_dict"):
            # the RunState's stats_logger_step cross-checks loop_state's
            # copy: whichever is further along wins (e.g. loop_state from
            # an older dump layout, or a jsonl lost with ephemeral disk)
            sl = dict(state.get("stats_logger") or {})
            sl["last_logged_step"] = max(
                int(sl.get("last_logged_step", -1)), info.stats_logger_step
            )
            stats_logger.load_state_dict(sl)
        prng = state.get("prng")
        if prng is not None:
            random.setstate(prng["python"])
            np.random.set_state(prng["numpy"])
        _, staleness, executor = _rollout_snapshot(rollout)
        if rollout is not None and hasattr(rollout, "set_version"):
            rollout.set_version(info.weight_version)
        if staleness is not None and info.rollout_stat:
            staleness.load_state_dict(info.rollout_stat)
        if executor is not None and state.get("drained"):
            executor.readmit_drained(
                [TimedResult(t=t, data=d) for t, d in state["drained"]],
                info.weight_version,
            )
        logger.info(
            "recovered from %s at global step %d (weight version %d)",
            root,
            info.last_step_info.global_step,
            info.weight_version,
        )
        return info

    def graceful_shutdown(
        self,
        engine,
        step: StepInfo,
        saver=None,
        evaluator=None,
        dataloader=None,
        stats_logger=None,
        *,
        fileroot: str,
        experiment_name: str,
        trial_name: str,
        tokenizer=None,
        config=None,
        rollout=None,
        guard: PreemptionGuard | None = None,
        checkpoint_reserve_seconds: float = 10.0,
        profiler=None,
    ) -> str | None:
        """The preemption path: drain in-flight episodes within the
        remaining grace budget (reserving ``checkpoint_reserve_seconds``
        for the dump itself), then force a recover dump that includes the
        drained rollouts. Returns the dump root. The caller exits with
        :data:`PREEMPTION_EXIT_CODE` after.

        Deliberately does NOT fan out a server-side pause: the drain's
        whole point is letting in-flight generations FINISH within the
        grace window, and a paused generation server aborts them (the
        client would spin on the pause flag until the budget burns with
        nothing salvaged). New episode launches are gated executor-side by
        ``drain()`` itself, and this process exits right after the dump —
        the servers simply go idle."""
        if profiler is not None:
            # finalize an in-flight jax.profiler capture FIRST: the
            # window may span the step we are abandoning, and an
            # unclosed capture is lost entirely (StepProfiler.close is
            # idempotent and swallows its own errors)
            profiler.close()
        budget = guard.remaining() if guard is not None else float("inf")
        if budget == float("inf"):
            budget = self.config.grace_period_seconds
        _, _, executor = _rollout_snapshot(rollout)
        drained: list[TimedResult] = []
        if executor is not None:
            drain_budget = max(
                0.0,
                min(
                    self.config.drain_timeout_seconds,
                    budget - checkpoint_reserve_seconds,
                ),
            )
            drained = executor.drain(timeout=drain_budget)
        # SIGTERM postmortem: dump the flight recorder's recent-event
        # rings next to the recover dump (best-effort; the checkpoint
        # below must proceed regardless)
        try:
            from areal_tpu.utils import flight_recorder

            flight_recorder.dump("sigterm")
        except Exception:
            logger.debug("sigterm flight dump failed", exc_info=True)
        return self.dump(
            engine,
            step,
            saver,
            evaluator,
            dataloader,
            stats_logger,
            fileroot=fileroot,
            experiment_name=experiment_name,
            trial_name=trial_name,
            tokenizer=tokenizer,
            config=config,
            force=True,
            rollout=rollout,
            drained=drained,
        )

    def protected_paths(
        self, fileroot: str, experiment_name: str, trial_name: str
    ) -> set[str]:
        """Checkpoint paths the retention GC must not delete: whatever the
        committed recover info currently names. Best-effort read — a
        missing or torn info file protects nothing (the GC separately
        always keeps the newest checkpoints)."""
        root = self.recover_root(fileroot, experiment_name, trial_name)
        p = self._read_marker(root).get("last_save_path")
        return {p} if p else set()
