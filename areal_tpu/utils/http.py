"""Async HTTP helpers (reference: areal/utils/http.py)."""

from __future__ import annotations

import asyncio
from typing import Any

import aiohttp

from areal_tpu.utils import logging

logger = logging.getLogger("http")


class HTTPRequestError(RuntimeError):
    pass


async def arequest_with_retry(
    session: aiohttp.ClientSession,
    url: str,
    method: str = "POST",
    payload: dict | None = None,
    data: bytes | None = None,
    max_retries: int = 3,
    timeout: float = 3600.0,
    retry_delay: float = 1.0,
) -> dict[str, Any]:
    """POST/GET with exponential-backoff retries; raises HTTPRequestError
    after exhausting retries."""
    last_exc: Exception | None = None
    for attempt in range(max_retries):
        try:
            async with session.request(
                method,
                url,
                json=payload,
                data=data,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status == 200:
                    return await resp.json()
                body = await resp.text()
                last_exc = HTTPRequestError(
                    f"{method} {url} -> {resp.status}: {body[:500]}"
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            last_exc = e
        if attempt + 1 < max_retries:
            await asyncio.sleep(retry_delay * 2**attempt)
    raise HTTPRequestError(f"{method} {url} failed after {max_retries} tries") from last_exc
