"""Async HTTP helpers (reference: areal/utils/http.py).

``arequest_with_retry`` is the single chokepoint every client->server
request in the rollout plane goes through, so its retry discipline is the
difference between graceful degradation and a retry storm:

- **status classification** — only 408/425/429/5xx and transport errors
  (connect/reset/timeout) retry; any other 4xx is the caller's bug (bad
  payload, wrong endpoint) and fails fast on the first attempt;
- **full-jitter exponential backoff** — delay ~ U(0, base * 2^attempt), so
  a fleet of clients recovering from the same outage doesn't re-stampede
  the server in lockstep;
- **Retry-After** — a 429/503 that says when to come back is honored (the
  floor of the next delay), seconds or HTTP-date form;
- **total deadline** — ``total_timeout`` bounds the whole call including
  backoff sleeps, so retries can never exceed the caller's budget;
- **chaos hook** — a :class:`~areal_tpu.utils.chaos.ChaosPolicy` injects
  deterministic faults through the same classification path a real failure
  takes. When ``chaos is None`` (production) the hot path pays exactly one
  None comparison: no awaits, no locks.

``sleep``/``clock``/``rng`` are injectable so chaos tests run with fake
time — no real sleeps in tier-1.
"""

from __future__ import annotations

import asyncio
import email.utils
import random
import time
from typing import Any

import aiohttp

from areal_tpu.utils import logging

logger = logging.getLogger("http")

#: statuses worth retrying: request-timeout, too-early, rate-limit, and the
#: 5xx family. Everything else in 4xx-land is deterministic caller error.
RETRIABLE_STATUSES = frozenset({408, 425, 429, 500, 502, 503, 504})

#: transport-level failures that retry (the connection, not the request,
#: was the problem). asyncio.TimeoutError != TimeoutError on py3.10.
TRANSPORT_ERRORS = (
    aiohttp.ClientError,
    asyncio.TimeoutError,
    TimeoutError,
    ConnectionError,
    OSError,
)


class HTTPRequestError(RuntimeError):
    def __init__(
        self,
        message: str,
        status: int | None = None,
        retriable: bool = True,
    ):
        super().__init__(message)
        self.status = status
        self.retriable = retriable


#: ceiling on a server-sent Retry-After: a misconfigured proxy saying
#: "come back tomorrow" (or "inf") must not stall a rollout slot — the
#: bounded exponential backoff resumes past this cap
RETRY_AFTER_CAP = 60.0


def _parse_retry_after(value: str | None) -> float | None:
    """Retry-After header -> seconds (delta-seconds or HTTP-date form),
    capped at :data:`RETRY_AFTER_CAP`; non-finite values are ignored."""
    if not value:
        return None
    import math

    try:
        secs = float(value)
        if not math.isfinite(secs):
            return None
        return min(RETRY_AFTER_CAP, max(0.0, secs))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    import datetime

    if dt.tzinfo is None:
        # parsedate_to_datetime returns a NAIVE datetime for a -0000 zone;
        # subtracting it from an aware `now` raises TypeError
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    now = datetime.datetime.now(datetime.timezone.utc)
    return min(RETRY_AFTER_CAP, max(0.0, (dt - now).total_seconds()))


async def arequest_with_retry(
    session: aiohttp.ClientSession,
    url: str,
    method: str = "POST",
    payload: dict | None = None,
    data: bytes | None = None,
    max_retries: int = 3,
    timeout: float = 3600.0,
    retry_delay: float = 1.0,
    total_timeout: float | None = None,
    chaos=None,
    headers: dict[str, str] | None = None,
    rng=None,
    sleep=None,
    clock=None,
) -> dict[str, Any]:
    """POST/GET with classified retries, full-jitter backoff, Retry-After,
    and a total-deadline budget; raises :class:`HTTPRequestError` on a
    non-retriable status or after exhausting retries/deadline."""
    rng = rng if rng is not None else random
    sleep = sleep if sleep is not None else asyncio.sleep
    clock = clock if clock is not None else time.monotonic
    deadline = (clock() + total_timeout) if total_timeout is not None else None
    last_exc: Exception | None = None
    attempt = 0
    while attempt < max_retries:
        attempt += 1
        retry_after: float | None = None
        try:
            per_try = timeout
            if deadline is not None:
                remaining = deadline - clock()
                if remaining <= 0:
                    raise HTTPRequestError(
                        f"{method} {url} exceeded total deadline "
                        f"{total_timeout}s after {attempt - 1} attempt(s)",
                        retriable=False,
                    ) from last_exc
                per_try = min(per_try, remaining)
            if chaos is not None:
                act = chaos.decide(url)
                if act is not None:
                    if act.kind == "slow":
                        await chaos.sleep(act.delay)
                    elif act.kind == "status":
                        raise HTTPRequestError(
                            f"{method} {url} -> {act.status}: chaos-injected",
                            status=act.status,
                            retriable=act.status in RETRIABLE_STATUSES,
                        )
                    elif act.kind == "disconnect":
                        raise aiohttp.ServerDisconnectedError(
                            "chaos-injected disconnect"
                        )
                    else:  # drop: the request vanished; client sees timeout
                        raise asyncio.TimeoutError("chaos-injected drop")
            # headers ride as an OPTIONAL kwarg: test doubles (scripted
            # sessions) keep their narrow request() signatures, and the
            # header-less common case stays byte-identical on the wire
            hdr_kw = {"headers": headers} if headers is not None else {}
            async with session.request(
                method,
                url,
                json=payload,
                data=data,
                timeout=aiohttp.ClientTimeout(total=per_try),
                **hdr_kw,
            ) as resp:
                if resp.status == 200:
                    return await resp.json()
                body = await resp.text()
                retry_after = _parse_retry_after(resp.headers.get("Retry-After"))
                raise HTTPRequestError(
                    f"{method} {url} -> {resp.status}: {body[:500]}",
                    status=resp.status,
                    retriable=resp.status in RETRIABLE_STATUSES,
                )
        except asyncio.CancelledError:
            raise
        except HTTPRequestError as e:
            if not e.retriable:
                raise  # fail fast: retrying a 404/400 only hides the bug
            last_exc = e
        except TRANSPORT_ERRORS as e:
            last_exc = e
        if attempt >= max_retries:
            break
        # full jitter: U(0, base * 2^(attempt-1)); Retry-After floors it
        delay = rng.uniform(0, retry_delay * 2 ** (attempt - 1))
        if retry_after is not None:
            delay = max(delay, retry_after)
        if deadline is not None:
            remaining = deadline - clock()
            if remaining <= 0:
                break
            delay = min(delay, remaining)
        await sleep(delay)
    raise HTTPRequestError(
        f"{method} {url} failed after {attempt} attempt(s): {last_exc}",
        status=getattr(last_exc, "status", None),
    ) from last_exc
