"""Training-plane step-time attribution: where did the step's wall-clock go?

The serving plane became explainable in PR 8 (traces, ``/metrics``, flight
recorder); this module is its trainer-side counterpart, the TPU heir of the
reference's analytic step accounting (realhf/base/monitor.py FLOPs counters
+ ``time_perf/*`` phase timers). Every train step decomposes into named
phases — rollout wait, logprob recompute, advantage, forward/backward +
optimizer, weight sync, checkpoint — and the timeline:

- **asserts attribution**: the recorded phases must sum to the step's
  wall-clock within ``tolerance`` (unattributed residual is exported as its
  own fraction and a breach warns once + bumps a counter — a growing
  residual means a new unnamed cost appeared in the loop);
- **derives goodput**: the compute fraction of the step (phases in
  ``COMPUTE_PHASES`` over wall), the number an async-RL throughput
  question actually asks for ("was the step rollout-bound or
  compute-bound?");
- **derives per-step MFU / TFLOPs-per-chip** from the existing analytic
  FLOPs math in :mod:`areal_tpu.utils.perf` (MFU is **absent, never
  zero**, when the chip peak is unknown — CPU rehearsal);
- **samples memory + recompile telemetry**: jax device ``memory_stats``
  gauges, live-array bytes, persistent-compilation-cache hit/miss
  counters, and the :class:`~areal_tpu.utils.jax_cache.RecompileDetector`
  (frozen after ``warmup_steps`` — a re-trace after that is the classic
  silent shape-bucket-miss throughput killer and warns exactly once);
- **exports everywhere the repo already looks**: scalars for the
  StatsLogger row (returned from :meth:`end_step` so the caller merges
  them like ``time_perf/*``), the PR 8 metrics registry (phase-seconds
  histograms, goodput/MFU gauges → ``/metrics`` and the periodic
  StatsLogger registry export), the flight recorder (``trainer`` channel:
  ring of recent breakdowns, dumped on watchdog/InjectedCrash/SIGTERM),
  and the PR 8 tracing plane — one ``train.step`` span per step stamped
  with the weight version the step PRODUCES, so a Perfetto export shows
  the train step next to the rollout episodes that consumed its weights
  (joined via the rollout spans' ``version`` attrs / ``weight_commit``
  events).

Step window protocol (matches the trainers' crash-exactness ordering,
where the stats row commits BEFORE the recover dump):

    timeline.begin_step(step)
    with timeline.phase("rollout"): ...
    with timeline.phase("train_step"): ...
    row = timeline.end_step(...)        # attribution window closes HERE
    stats_logger.commit(..., {**stats, **row})
    with timeline.phase("checkpoint"):  # LATE phase: after end_step
        saver.save(); recover.dump()
    # next begin_step (or close()) finalizes: span ends, flight-recorder
    # entry written — late phases ride the span/record but are excluded
    # from the attribution sum, whose contract is the end_step window.

Cost contract: tracing off ⇒ the only tracing cost is ``is not None``
checks (the PR 8 chaos-hook discipline, pinned by the code-inspection
test); the timeline itself runs once per STEP, never per token.
"""

from __future__ import annotations

import contextlib
import time

from areal_tpu.utils import logging

logger = logging.getLogger("StepTimeline")

#: phases counted as "useful training compute" for the goodput fraction;
#: everything else (rollout wait, weight sync, checkpoint, unattributed)
#: is coordination the async design tries to overlap away.
COMPUTE_PHASES = frozenset(
    {"train_step", "recompute_logp", "ref_logp", "compute_advantage"}
)

#: flight-recorder channel holding the ring of recent step breakdowns
TRAINER_CHANNEL = "trainer"


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullContext()


class StepTimeline:
    """Per-step phase attribution + goodput/MFU accounting.

    All clocks are injectable (tests drive fake time); ``peak_flops``
    overrides the chip peak for MFU (None = resolve from the device,
    which yields no MFU off-TPU — absent, never zero).
    """

    def __init__(
        self,
        config=None,
        tracer=None,
        model_config=None,
        n_chips: int = 1,
        recorder=None,
        registry=None,
        clock=time.perf_counter,
        peak_flops: float | None = None,
    ):
        self.config = config
        self.enabled = config is None or getattr(config, "enabled", True)
        self._tracer = tracer
        self.model_config = model_config
        self.n_chips = max(1, int(n_chips))
        self._clock = clock
        self._peak_flops = peak_flops
        tol = getattr(config, "tolerance", 0.05)
        self.tolerance = 0.05 if tol is None else float(tol)
        self.warmup_steps = int(getattr(config, "warmup_steps", 2))
        self.memory_telemetry = bool(
            getattr(config, "memory_telemetry", True)
        )
        self.recompile_detector = bool(
            getattr(config, "recompile_detector", True)
        )
        if recorder is None:
            from areal_tpu.utils import flight_recorder

            recorder = flight_recorder.DEFAULT_RECORDER
        self._recorder = recorder
        recorder.channel(
            TRAINER_CHANNEL,
            capacity=int(getattr(config, "trainer_channel_steps", 64)),
        )
        if registry is None:
            from areal_tpu.utils import metrics

            registry = metrics.DEFAULT_REGISTRY
        self._registry = registry
        self._phase_hist = registry.histogram(
            "areal_train_phase_seconds",
            "per-phase train-step wall time",
            labels=("phase",),
        )
        self._step_hist = registry.histogram(
            "areal_train_step_seconds", "train-step wall time"
        )
        self._goodput_g = registry.gauge(
            "areal_train_goodput", "compute fraction of the last train step"
        )
        self._unattr_g = registry.gauge(
            "areal_train_unattributed_fraction",
            "step wall-clock not covered by any recorded phase",
        )
        self._breach_c = registry.counter(
            "areal_train_attribution_breaches_total",
            "steps whose phase sum missed wall-clock beyond tolerance",
        )
        self._mem_g = registry.gauge(
            "areal_jax_memory_bytes",
            "jax device memory_stats sampled per step (absent off-TPU)",
            labels=("stat",),
        )
        self._live_g = registry.gauge(
            "areal_jax_live_array_bytes",
            "total bytes of live jax arrays sampled per step",
        )
        self._mfu_g = registry.gauge(
            "areal_train_mfu",
            "per-step model FLOPs utilization (absent when peak unknown)",
            labels=("device_kind",),
        )
        self._tflops_g = registry.gauge(
            "areal_train_tflops_per_chip",
            "per-step achieved TFLOP/s per chip (analytic FLOPs)",
            labels=("device_kind",),
        )
        # telemetry hooks shared with the rest of the process
        from areal_tpu.utils import jax_cache

        self._detector = (
            jax_cache.DEFAULT_DETECTOR if self.recompile_detector else None
        )
        if self.enabled:
            jax_cache.install_cache_event_counters(registry)
        self._span = None
        self._record: dict | None = None
        self._phases: dict[str, float] = {}
        self._late_phases: dict[str, float] = {}
        self._t_begin = 0.0
        self._closed_step = True  # no step open yet
        self._steps_seen = 0
        self._warned_tolerance = False
        self._device_kind: str | None = None

    @property
    def span(self):
        """The step's open ``train.step`` tracing span (None with tracing
        off or between steps) — co-plane observers (the RL-health monitor)
        stamp their events onto it so one Perfetto export shows algorithm
        health next to the phase breakdown."""
        return self._span

    @classmethod
    def from_config(cls, config, **kwargs) -> "StepTimeline":
        """Always returns a timeline; a disabled config yields one whose
        begin/phase/end are no-ops (per-STEP cost only, nothing per
        token), so trainer loops need no conditional plumbing."""
        return cls(config=config, **kwargs)

    # ----------------------------------------------------------- recording

    def begin_step(self, global_step: int) -> None:
        """Open the attribution window for one step; finalizes the
        previous step's record (span end + flight-recorder entry) so late
        phases (checkpoint) land on the step that ran them."""
        if not self.enabled:
            return
        self._finalize()
        self._phases = {}
        self._late_phases = {}
        self._record = {"step": int(global_step)}
        self._t_begin = self._clock()
        self._closed_step = False
        if self._tracer is not None:
            self._span = self._tracer.span("train.step", step=int(global_step))

    def phase(self, name: str):
        """Context manager timing one named phase. Inside the step window
        it counts toward the attribution sum; after :meth:`end_step` it is
        recorded as a LATE phase (rides the span/flight record, excluded
        from the sum — the checkpoint-after-commit ordering)."""
        if not self.enabled or self._record is None:
            return _NULL
        return self._phase_cm(name)

    @contextlib.contextmanager
    def _phase_cm(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            dur = self._clock() - t0
            target = self._late_phases if self._closed_step else self._phases
            target[name] = target.get(name, 0.0) + dur
            self._phase_hist.labels(phase=name).observe(dur)
            if self._span is not None:
                self._span.event("phase", phase=name, dur=dur)

    def end_step(
        self,
        tokens: int | None = None,
        n_seqs: int | None = None,
        weight_version: int | None = None,
        extra: dict | None = None,
    ) -> dict[str, float]:
        """Close the attribution window; returns the ``step_timeline/*``
        scalar row for the StatsLogger commit. ``tokens``/``n_seqs``
        (trained tokens and sequences this step) unlock TFLOPs/MFU via
        the analytic FLOPs math; ``weight_version`` stamps the version
        this step PRODUCED onto the span and record (the cross-plane
        Perfetto join key)."""
        if not self.enabled or self._record is None or self._closed_step:
            return {}
        wall = max(self._clock() - self._t_begin, 0.0)
        self._closed_step = True
        self._steps_seen += 1
        accounted = sum(self._phases.values())
        unattr = wall - accounted
        unattr_frac = (unattr / wall) if wall > 0 else 0.0
        compute = sum(
            v for k, v in self._phases.items() if k in COMPUTE_PHASES
        )
        goodput = (compute / wall) if wall > 0 else 0.0
        if wall > 0 and abs(unattr_frac) > self.tolerance:
            self._breach_c.inc()
            if not self._warned_tolerance:
                self._warned_tolerance = True
                logger.warning(
                    "step attribution breach: phases sum to %.4fs but the "
                    "step took %.4fs (%.1f%% unattributed > %.0f%% "
                    "tolerance) — a cost in the loop has no phase around "
                    "it (warned once; counted on "
                    "areal_train_attribution_breaches_total)",
                    accounted,
                    wall,
                    unattr_frac * 100.0,
                    self.tolerance * 100.0,
                )
        row: dict[str, float] = {
            f"step_timeline/{k}": v for k, v in self._phases.items()
        }
        row["step_timeline/wall"] = wall
        row["step_timeline/unattributed"] = unattr
        row["step_timeline/unattributed_frac"] = unattr_frac
        row["step_timeline/goodput"] = goodput
        self._step_hist.observe(wall)
        self._goodput_g.set(goodput)
        self._unattr_g.set(unattr_frac)
        row.update(self._perf_row(wall, tokens, n_seqs))
        row.update(self._telemetry_row())
        if extra:
            row.update({f"step_timeline/{k}": v for k, v in extra.items()})
        rec = self._record
        rec.update(
            wall=wall,
            phases=dict(self._phases),
            goodput=goodput,
            unattributed_frac=unattr_frac,
        )
        if weight_version is not None:
            rec["version"] = int(weight_version)
        if tokens is not None:
            rec["tokens"] = int(tokens)
        if self._span is not None:
            self._span.set(
                wall=wall,
                goodput=round(goodput, 4),
                unattributed_frac=round(unattr_frac, 4),
            )
            if weight_version is not None:
                self._span.set(version=int(weight_version))
        # freeze the recompile detector once warmup (compile/bucket
        # discovery) is over: any trace after this is a flagged re-trace
        # (>=, not ==: warmup_steps=0 / a resumed counter must still
        # freeze at the first completed step)
        if (
            self._detector is not None
            and not self._detector.frozen
            and self._steps_seen >= self.warmup_steps
        ):
            self._detector.freeze()
        return row

    def close(self) -> None:
        """Finalize the open step (loop exit / graceful drain): ends the
        span and writes the last flight-recorder entry."""
        if not self.enabled:
            return
        self._finalize()

    # ------------------------------------------------------------ internals

    def _finalize(self) -> None:
        rec, self._record = self._record, None
        if rec is None:
            return
        if self._late_phases:
            rec["late_phases"] = dict(self._late_phases)
        self._recorder.record(TRAINER_CHANNEL, "step", **rec)
        if self._span is not None:
            self._span.end()
            self._span = None

    def _perf_row(
        self, wall: float, tokens: int | None, n_seqs: int | None
    ) -> dict[str, float]:
        """TFLOPs-per-chip + MFU over the FULL step wall (the goodput-
        style utilization number: rollout waits count against it). MFU is
        omitted — not zeroed — when the chip peak is unknown (CPU)."""
        if (
            tokens is None
            or tokens <= 0
            or wall <= 0
            or self.model_config is None
        ):
            return {}
        from areal_tpu.utils import perf

        avg_seqlen = tokens / max(int(n_seqs or 1), 1)
        fpt = perf.train_flops_per_token(self.model_config, avg_seqlen)
        tps = tokens / wall
        kind = self._resolve_device_kind()
        tflops = tps * fpt / self.n_chips / 1e12
        self._tflops_g.labels(device_kind=kind).set(tflops)
        out = {
            "step_timeline/tokens_per_sec": tps,
            "step_timeline/tflops_per_chip": tflops,
        }
        m = perf.mfu(tps, fpt, n_chips=self.n_chips, peak=self._peak_flops)
        if m is not None:
            out["step_timeline/mfu"] = m
            self._mfu_g.labels(device_kind=kind).set(m)
        return out

    def _resolve_device_kind(self) -> str:
        if self._device_kind is None:
            from areal_tpu.utils import perf

            self._device_kind = perf.device_kind()
        return self._device_kind

    def _telemetry_row(self) -> dict[str, float]:
        out: dict[str, float] = {}
        if self.memory_telemetry:
            try:
                import jax

                dev = jax.local_devices()[0]
                stats = dev.memory_stats()
                if stats:
                    for key in ("bytes_in_use", "peak_bytes_in_use"):
                        v = stats.get(key)
                        if v is not None:
                            self._mem_g.labels(stat=key).set(float(v))
                            out[f"step_timeline/memory_{key}"] = float(v)
                live = sum(int(a.nbytes) for a in jax.live_arrays())
                self._live_g.set(float(live))
                out["step_timeline/live_array_bytes"] = float(live)
            except Exception:  # telemetry must never fail the step
                logger.exception("memory telemetry sample failed")
        if self._detector is not None:
            retraces = self._detector.total_retraces()
            if retraces:
                out["step_timeline/jit_retraces_after_warmup"] = float(
                    retraces
                )
        return out
