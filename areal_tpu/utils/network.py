"""Host networking helpers (free ports, host IP).

Parity: reference ``areal/utils/network.py`` (find_free_ports / gethostip).
"""

from __future__ import annotations

import socket
from contextlib import closing


def find_free_ports(count: int = 1, low: int = 10000, high: int = 60000) -> list[int]:
    ports: list[int] = []
    socks = []
    try:
        while len(ports) < count:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port = s.getsockname()[1]
            if low <= port <= high and port not in ports:
                ports.append(port)
                socks.append(s)  # hold open so the next bind can't collide
            else:
                s.close()
    finally:
        for s in socks:
            s.close()
    return ports


def find_free_port(**kwargs) -> int:
    return find_free_ports(1, **kwargs)[0]


def gethostip() -> str:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"


def gethostname() -> str:
    return socket.gethostname()
