"""Cross-process device-path weight transfer (the reference's dedicated
NCCL broadcast group for trainer->server weight resync,
areal/engine/fsdp_engine.py:359-401, re-based on JAX's transfer service).

``jax.experimental.transfer`` moves device buffers directly between two
independent JAX processes (no shared jax.distributed world needed): the
publisher stages arrays with ``await_pull(uuid, ...)``; the consumer
connects and ``pull``s into its own devices. No safetensors serialization,
no HTTP body, no host-RAM staging of the payload — on TPU the data plane
is the platform's DMA path, on CPU a socket stream between device
allocations.

Contract (v1): every published leaf is SINGLE-SHARD (the publisher
gathers each chunk to one device first — the same rank-0-materializes
shape as an NCCL broadcast); the consumer pulls each leaf onto one of its
devices and re-shards locally. Chunking bounds the transient single-device
footprint on both sides.

One transfer server per process, shared by all connections; creation is
lazy so pure-HTTP deployments never bind the extra port.
"""

from __future__ import annotations

import threading

from areal_tpu.utils import logging

logger = logging.getLogger("DeviceTransfer")

_LOCK = threading.Lock()
_SERVER = None
_CONNECTIONS: dict[str, object] = {}
_UUID_COUNTER = 0
# staged-but-unacknowledged bytes: stage_for_pull adds, ack_pulled (the
# publisher, after every consumer confirmed its pull) subtracts. Entries
# from FAILED pushes are never acked — their bytes stay on the books, and
# each new push attempt logs the leak so mounting HBM pressure is visible
# BEFORE it turns into opaque allocation failures.
_STAGED_UNACKED_BYTES = 0


def staged_unacked_bytes() -> int:
    """Cumulative bytes staged via :func:`stage_for_pull` whose pulls were
    never acknowledged — the device memory one-shot await_pull entries pin
    until process exit."""
    with _LOCK:
        return _STAGED_UNACKED_BYTES


def ack_pulled(nbytes: int) -> None:
    """Publisher-side acknowledgement that consumers pulled ``nbytes``
    worth of staged entries (e.g. every server's HTTP response arrived) —
    those entries no longer pin device memory."""
    global _STAGED_UNACKED_BYTES
    with _LOCK:
        _STAGED_UNACKED_BYTES = max(0, _STAGED_UNACKED_BYTES - int(nbytes))


def next_uuid_block(count: int) -> int:
    """Reserve ``count`` process-unique uuids; returns the first.

    await_pull entries are one-shot and cannot be withdrawn: a FAILED push
    attempt leaves its staged entries registered (bounded device memory
    held until process exit). Fresh uuids per attempt guarantee a retry
    can never consume a stale staged chunk from the failed one. Called
    once per push attempt, so this is where a leak from earlier attempts
    gets surfaced."""
    global _UUID_COUNTER
    with _LOCK:
        leaked = _STAGED_UNACKED_BYTES
        base = _UUID_COUNTER
        _UUID_COUNTER += count
    if leaked:
        logger.warning(
            "starting a push attempt with %.1f MB of staged-but-unpulled "
            "transfer entries from earlier failed attempts still pinning "
            "device memory (one-shot await_pull entries cannot be "
            "withdrawn; they free only on process exit)",
            leaked / 1e6,
        )
    return base


def transfer_server(bind_host: str | None = None):
    """The process-wide transfer server (created on first use)."""
    global _SERVER
    with _LOCK:
        if _SERVER is None:
            import jax
            import jax.experimental.transfer as xfer

            if bind_host is None:
                from areal_tpu.utils.network import gethostip

                bind_host = gethostip()
            client = jax.devices()[0].client
            # explicit bulk-transport address: the default local-transport
            # path aborts on this backend (streaming.cc check failure)
            _SERVER = xfer.start_transfer_server(
                client, f"{bind_host}:0", [f"{bind_host}:0"]
            )
            logger.info("transfer server on %s", _SERVER.address())
    return _SERVER


def transfer_address(bind_host: str | None = None) -> str:
    return transfer_server(bind_host).address()


def connect(address: str):
    """Cached connection to a peer's transfer server."""
    srv = transfer_server()  # before the lock: it takes _LOCK itself
    with _LOCK:
        conn = _CONNECTIONS.get(address)
        if conn is None:
            conn = srv.connect(address)
            _CONNECTIONS[address] = conn
        return conn


def stage_for_pull(uuid: int, arrays, account: bool = True) -> int:
    """Publish a pytree for exactly one remote ``pull(uuid, ...)``.
    Returns the byte count of ``arrays`` (pass it to :func:`ack_pulled`
    once the consumer confirmed the pull). ``account=False`` skips the
    unacked-bytes ledger: when the SAME array set is staged under several
    uuids (one per consumer), the underlying buffers are shared and pin
    device memory once — account only the first staging, or the leak
    warning overstates by the consumer count."""
    global _STAGED_UNACKED_BYTES
    import jax

    nbytes = sum(
        int(getattr(leaf, "nbytes", leaf.size * leaf.dtype.itemsize))
        for leaf in jax.tree_util.tree_leaves(arrays)
    )
    if account:
        with _LOCK:
            _STAGED_UNACKED_BYTES += nbytes
    transfer_server().await_pull(uuid, arrays)
    return nbytes


def pull(address: str, uuid: int, specs):
    """Fetch a pytree of ShapeDtypeStructs (with shardings) from a peer."""
    return connect(address).pull(uuid, specs)


class PrefetchIterator:
    """Bounded background-thread producer over a chunk iterator.

    The weight-sync chunk generators do real work per ``next()`` — a host
    gather (``_weight_chunks``) or a single-shard device gather
    (``_weight_chunks_device``) — which used to run INSIDE the transfer
    loop, serializing gather/encode with the wire. Wrapping the generator
    here runs that work on a daemon thread up to ``depth`` chunks ahead, so
    chunk ``i+1`` gathers while chunk ``i`` is in flight, with host/device
    staging RAM bounded at ``depth`` chunks beyond the consumer's.

    Exceptions from the source iterator are re-raised at the consuming
    ``next()`` call (wrapped exactly once, original traceback preserved).
    A consumer that abandons the iterator mid-stream should call
    :meth:`close` so the producer thread exits and drops its held chunks
    (a host-gathered chunk can be chunked_mem_mb large; parking it on the
    queue for the process lifetime is real RAM).
    """

    _SENTINEL = object()

    def __init__(self, source, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        import queue as _queue

        self._queue: "_queue.Queue" = _queue.Queue(maxsize=depth)
        self._source = source
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="weight-chunk-prefetch", daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        """Blocking put that aborts when the consumer closed the iterator
        (a plain put would park this thread — and the chunk it holds —
        forever once the consumer is gone)."""
        import queue as _queue

        while not self._closed:
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _run(self):
        try:
            for item in self._source:
                if not self._put(item):
                    return  # closed: drop held chunks, exit the thread
        except BaseException as e:  # noqa: BLE001 — re-raised at next()
            self._put((self._SENTINEL, e))
        else:
            self._put((self._SENTINEL, None))

    def close(self):
        """Release the producer thread and drop buffered chunks. Idempotent;
        safe to call with the producer blocked mid-put."""
        self._closed = True
        import queue as _queue

        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if isinstance(item, tuple) and len(item) == 2 and item[0] is self._SENTINEL:
            self._queue.put(item)  # keep the stream terminal for re-calls
            if item[1] is None:
                raise StopIteration
            raise item[1]
        return item
