"""Cross-process device-path weight transfer (the reference's dedicated
NCCL broadcast group for trainer->server weight resync,
areal/engine/fsdp_engine.py:359-401, re-based on JAX's transfer service).

``jax.experimental.transfer`` moves device buffers directly between two
independent JAX processes (no shared jax.distributed world needed): the
publisher stages arrays with ``await_pull(uuid, ...)``; the consumer
connects and ``pull``s into its own devices. No safetensors serialization,
no HTTP body, no host-RAM staging of the payload — on TPU the data plane
is the platform's DMA path, on CPU a socket stream between device
allocations.

Contract (v1): every published leaf is SINGLE-SHARD (the publisher
gathers each chunk to one device first — the same rank-0-materializes
shape as an NCCL broadcast); the consumer pulls each leaf onto one of its
devices and re-shards locally. Chunking bounds the transient single-device
footprint on both sides.

One transfer server per process, shared by all connections; creation is
lazy so pure-HTTP deployments never bind the extra port.
"""

from __future__ import annotations

import threading

from areal_tpu.utils import logging

logger = logging.getLogger("DeviceTransfer")

_LOCK = threading.Lock()
_SERVER = None
_CONNECTIONS: dict[str, object] = {}
_UUID_COUNTER = 0


def next_uuid_block(count: int) -> int:
    """Reserve ``count`` process-unique uuids; returns the first.

    await_pull entries are one-shot and cannot be withdrawn: a FAILED push
    attempt leaves its staged entries registered (bounded device memory
    held until process exit). Fresh uuids per attempt guarantee a retry
    can never consume a stale staged chunk from the failed one."""
    global _UUID_COUNTER
    with _LOCK:
        base = _UUID_COUNTER
        _UUID_COUNTER += count
        return base


def transfer_server(bind_host: str | None = None):
    """The process-wide transfer server (created on first use)."""
    global _SERVER
    with _LOCK:
        if _SERVER is None:
            import jax
            import jax.experimental.transfer as xfer

            if bind_host is None:
                from areal_tpu.utils.network import gethostip

                bind_host = gethostip()
            client = jax.devices()[0].client
            # explicit bulk-transport address: the default local-transport
            # path aborts on this backend (streaming.cc check failure)
            _SERVER = xfer.start_transfer_server(
                client, f"{bind_host}:0", [f"{bind_host}:0"]
            )
            logger.info("transfer server on %s", _SERVER.address())
    return _SERVER


def transfer_address(bind_host: str | None = None) -> str:
    return transfer_server(bind_host).address()


def connect(address: str):
    """Cached connection to a peer's transfer server."""
    srv = transfer_server()  # before the lock: it takes _LOCK itself
    with _LOCK:
        conn = _CONNECTIONS.get(address)
        if conn is None:
            conn = srv.connect(address)
            _CONNECTIONS[address] = conn
        return conn


def stage_for_pull(uuid: int, arrays) -> None:
    """Publish a pytree for exactly one remote ``pull(uuid, ...)``."""
    transfer_server().await_pull(uuid, arrays)


def pull(address: str, uuid: int, specs):
    """Fetch a pytree of ShapeDtypeStructs (with shardings) from a peer."""
    return connect(address).pull(uuid, specs)
