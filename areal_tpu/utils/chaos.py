"""Deterministic fault injection for the rollout client<->server HTTP path.

Every fault-tolerance behavior in the client plane (circuit breakers,
failover re-dispatch, degraded weight-update fan-out) is exercised by
*deterministic* chaos rather than hope: a :class:`ChaosPolicy` holds a
seeded RNG plus per-endpoint rules (drop, http_error/5xx, timeout,
slow-response, disconnect-mid-stream, fail-next-N) and is hookable into

- the client side: ``arequest_with_retry(..., chaos=policy)``
  (areal_tpu/utils/http.py) — the injected fault goes through the *same*
  retry/classification path a real failure would;
- the server side: :func:`aiohttp_chaos_middleware` installed by
  ``GenerationServer`` when the ``AREAL_CHAOS_SERVER`` env var carries a
  JSON policy (or a policy is passed explicitly in tests).

Zero overhead when off: the client hook is a single ``chaos is not None``
check, and the server middleware is simply not installed.

Determinism: rules default to ``probability=1.0`` and ``times=N``
(fail-next-N), in which case the RNG is never consulted; probabilistic
rules draw from ``random.Random(seed)`` so a run replays exactly. The
``sleep`` used for slow/drop actions is injectable so tests advance a fake
clock instead of waiting.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import random
from typing import TYPE_CHECKING

from areal_tpu.utils import logging

if TYPE_CHECKING:  # pragma: no cover
    from areal_tpu.api.cli_args import ChaosConfig

logger = logging.getLogger("chaos")

CHAOS_SERVER_ENV = "AREAL_CHAOS_SERVER"

# ---------------------------------------------------------------------------
# deterministic crash points (the preemption-safety harness)
# ---------------------------------------------------------------------------

CRASH_ENV = "AREAL_CRASH_AT"

#: barrier names the training plane exposes; harness loops may add their own
CRASH_POINTS = (
    "pre-rollout-wait",   # WorkflowExecutor.wait entry
    "post-train-step",    # after the optimizer step, before weight push
    "pre-weight-update",  # before the weight fan-out to inference servers
    "mid-checkpoint",     # inside RecoverHandler.dump, before the commit marker
)


class InjectedCrash(BaseException):
    """Raised by :func:`crash_point` to simulate a kill -9 at an exact
    barrier. A ``BaseException`` on purpose: generic ``except Exception``
    retry/cleanup paths must not swallow it — a real SIGKILL would not be
    swallowed either. Only the crash-test harness catches it."""


#: per-name hit counters for ``name@N`` specs (crash on the Nth arrival)
_crash_hits: dict[str, int] = {}


def reset_crash_points() -> None:
    """Clear hit counters (tests arm a fresh spec per scenario)."""
    _crash_hits.clear()


def crash_point(name: str) -> None:
    """Deterministic kill barrier: if ``AREAL_CRASH_AT`` names this point,
    raise :class:`InjectedCrash` here. Spec grammar, comma-separated:
    ``point`` (crash on first arrival) or ``point@N`` (crash on the Nth).
    Off (the common case) costs one env lookup at a once-per-step site —
    these barriers never sit in token-level hot loops."""
    spec = os.environ.get(CRASH_ENV, "")
    if not spec:
        return
    for part in spec.split(","):
        target, _, nth = part.strip().partition("@")
        if target != name:
            continue
        _crash_hits[name] = _crash_hits.get(name, 0) + 1
        n = int(nth) if nth else 1
        if _crash_hits[name] == n:
            # an injected kill leaves the same postmortem artifact a real
            # one would: the flight recorder's recent-event rings (the
            # whole point of the chaos harness is rehearsing production
            # failures end to end, evidence included)
            try:
                from areal_tpu.utils import flight_recorder

                flight_recorder.dump(f"injected_crash_{name}")
            except Exception:
                logger.debug("pre-crash flight dump failed", exc_info=True)
            raise InjectedCrash(
                f"AREAL_CRASH_AT barrier {name!r} (arrival {n})"
            )

# ---------------------------------------------------------------------------
# deterministic filesystem faults (the checkpoint-durability harness)
# ---------------------------------------------------------------------------

FS_CHAOS_ENV = "AREAL_CHAOS_FS"

#: fault kinds the atomic-write helpers inject; every kind aborts BEFORE
#: the commit rename, because that is what the real failures do — a full
#: disk or dying device tears the tmp file, never the committed one
FS_FAULT_KINDS = (
    "enospc",  # OSError(ENOSPC) before any bytes land (disk full)
    "eio",     # OSError(EIO) at fsync (device error after a full write)
    "short",   # tmp truncated to half, then OSError (torn write + crash)
)

#: per-spec arrival counters for ``substr:kind@N`` specs
_fs_fault_hits: dict[str, int] = {}


def reset_fs_faults() -> None:
    """Clear arrival counters (tests arm a fresh spec per scenario)."""
    _fs_fault_hits.clear()


def fs_fault(path: str) -> str | None:
    """Deterministic filesystem fault gate for the atomic-write helpers.
    ``AREAL_CHAOS_FS`` holds comma-separated specs
    ``<path-substr>:<kind>`` (fault on the first write whose destination
    contains the substring) or ``<path-substr>:<kind>@N`` (the Nth such
    write). Returns the fault kind to inject for THIS write, or None.
    Only consulted when the env var is set — the off path in
    ``utils/fs.atomic_write`` is a single env lookup."""
    spec = os.environ.get(FS_CHAOS_ENV, "")
    if not spec:
        return None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        substr, _, rest = part.partition(":")
        kind, _, nth = rest.partition("@")
        if kind not in FS_FAULT_KINDS:
            raise ValueError(
                f"unknown {FS_CHAOS_ENV} fault kind {kind!r} in {part!r}; "
                f"one of {FS_FAULT_KINDS}"
            )
        if substr not in path:
            continue
        _fs_fault_hits[part] = _fs_fault_hits.get(part, 0) + 1
        if _fs_fault_hits[part] == (int(nth) if nth else 1):
            logger.warning(
                "chaos: fs fault %r injected on write to %s (arrival %d)",
                kind,
                path,
                _fs_fault_hits[part],
            )
            return kind
    return None


# ---------------------------------------------------------------------------
# deterministic RL-signal faults (the training-health sentinel harness)
# ---------------------------------------------------------------------------

RL_CHAOS_ENV = "AREAL_CHAOS_RL"

#: fault names the RL-health observatory consults; each corrupts ONE
#: health signal in the observed snapshot (never the training math), so
#: the sentinel's detection/guardrail path is exercised end to end
RL_FAULTS = (
    "nan_loss",          # loss/grad_norm turn non-finite
    "entropy_collapse",  # entropy estimate pinned to ~0
    "staleness_spike",   # staleness p95/max jump past any threshold
    "ratio_blowup",      # importance-ratio p99 jumps past the cap
    "reward_flatline",   # rewards read as a constant
    "repetition_spike",  # degenerate-output fraction pinned to 1.0
)

#: per-name arrival counters for ``name@N[:K]`` specs
_rl_fault_hits: dict[str, int] = {}


def reset_rl_faults() -> None:
    """Clear arrival counters (tests arm a fresh spec per scenario)."""
    _rl_fault_hits.clear()


def rl_fault(name: str) -> bool:
    """Deterministic RL-signal fault gate, mirroring :func:`crash_point`'s
    grammar: ``AREAL_CHAOS_RL`` holds comma-separated specs ``name`` (fault
    on the first arrival), ``name@N`` (the Nth), or ``name@N:K`` (arrivals
    N..N+K-1 — K consecutive steps, for exercising sentinel hysteresis).
    Returns True when THIS arrival is inside the armed window. Only called
    by the RL-health monitor (already behind its enabled gate), once per
    step — never in token-level loops."""
    spec = os.environ.get(RL_CHAOS_ENV, "")
    if not spec:
        return False
    for part in spec.split(","):
        target, _, window = part.strip().partition("@")
        if target != name:
            continue
        _rl_fault_hits[name] = _rl_fault_hits.get(name, 0) + 1
        start_s, _, width_s = window.partition(":")
        start = int(start_s) if start_s else 1
        width = int(width_s) if width_s else 1
        if start <= _rl_fault_hits[name] < start + width:
            logger.warning(
                "chaos: RL fault %r injected (arrival %d)",
                name,
                _rl_fault_hits[name],
            )
            return True
    return False


# ---------------------------------------------------------------------------
# deterministic generation-interrupt injection (token-boundary interruption)
# ---------------------------------------------------------------------------

INTERRUPT_CHAOS_ENV = "AREAL_CHAOS_INTERRUPT"

#: site names the generation engine consults; each fires an interrupt at
#: one adversarial point of the serving lifecycle (see engine._chaos_interrupt)
INTERRUPT_SITES = (
    "mid-commit",           # right after a staged weight commit flips
    "mid-chunked-prefill",  # between chunks of an intra-prompt warm
    "radix-warm",           # right after a radix hit enters chunked warm
)

#: per-site arrival counters for ``name@N[:K]`` specs
_interrupt_hits: dict[str, int] = {}


def reset_interrupt_points() -> None:
    """Clear arrival counters (tests arm a fresh spec per scenario)."""
    _interrupt_hits.clear()


def interrupt_point(name: str) -> bool:
    """Deterministic interrupt-injection gate, same grammar as
    :func:`rl_fault`: ``AREAL_CHAOS_INTERRUPT`` holds comma-separated specs
    ``name`` (fire on the first arrival), ``name@N`` (the Nth), or
    ``name@N:K`` (arrivals N..N+K-1). Returns True when THIS arrival is
    inside the armed window — the engine then interrupts a running/warming
    sequence at that exact point. Called from engine-loop sites only (never
    per token); off = one env lookup."""
    spec = os.environ.get(INTERRUPT_CHAOS_ENV, "")
    if not spec:
        return False
    for part in spec.split(","):
        target, _, window = part.strip().partition("@")
        if target != name:
            continue
        _interrupt_hits[name] = _interrupt_hits.get(name, 0) + 1
        start_s, _, width_s = window.partition(":")
        start = int(start_s) if start_s else 1
        width = int(width_s) if width_s else 1
        if start <= _interrupt_hits[name] < start + width:
            logger.warning(
                "chaos: generation interrupt fired at %r (arrival %d)",
                name,
                _interrupt_hits[name],
            )
            return True
    return False


#: action vocabulary shared by config validation and the two hook sites
ACTIONS = ("drop", "http_error", "timeout", "slow", "disconnect")


@dataclasses.dataclass(frozen=True)
class ChaosAction:
    """A decided fault for one request.

    ``kind`` is the *effect* vocabulary, not the rule vocabulary:
    "status" (synthesized HTTP error), "slow" (delay then proceed),
    "disconnect" (sever the connection), "drop" (the request vanishes —
    the client perceives a timeout, the server never answers).
    """

    kind: str
    status: int = 503
    delay: float = 0.0


class _Rule:
    __slots__ = ("endpoint", "action", "probability", "status", "delay", "remaining")

    def __init__(
        self,
        endpoint: str = "*",
        action: str = "http_error",
        probability: float = 1.0,
        status: int = 503,
        delay: float = 0.0,
        times: int = 0,
    ):
        if action not in ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}; one of {ACTIONS}")
        self.endpoint = endpoint
        self.action = action
        self.probability = probability
        self.status = status
        self.delay = delay
        self.remaining = times if times > 0 else None  # None = unlimited

    def matches(self, path: str) -> bool:
        return self.endpoint == "*" or self.endpoint in path

    def describe(self) -> str:
        n = "inf" if self.remaining is None else str(self.remaining)
        return f"{self.endpoint}:{self.action}(p={self.probability},n={n})"


def _effect(rule: _Rule) -> ChaosAction:
    if rule.action == "http_error":
        return ChaosAction(kind="status", status=rule.status, delay=rule.delay)
    if rule.action == "slow":
        return ChaosAction(kind="slow", delay=rule.delay)
    if rule.action == "disconnect":
        return ChaosAction(kind="disconnect", delay=rule.delay)
    # drop and timeout share the effect: no answer ever comes back
    return ChaosAction(kind="drop", delay=rule.delay)


class ChaosPolicy:
    """Seeded, per-endpoint fault decisions. One instance per hook site
    (client engine or server); not shared across threads."""

    def __init__(self, rules: list[_Rule] | None = None, seed: int = 0, sleep=None):
        self._rules: list[_Rule] = list(rules or [])
        self._rng = random.Random(seed)
        self.sleep = sleep if sleep is not None else asyncio.sleep
        self.injected = 0  # total faults decided (tests/telemetry)

    # -- construction ---------------------------------------------------

    @classmethod
    def from_config(cls, cfg: "ChaosConfig | None", sleep=None) -> "ChaosPolicy | None":
        """None when chaos is off — callers then pay only a None check."""
        if cfg is None or not cfg.enabled or not cfg.rules:
            return None
        rules = [
            _Rule(
                endpoint=r.endpoint,
                action=r.action,
                probability=r.probability,
                status=r.status,
                delay=r.delay_seconds,
                times=r.times,
            )
            for r in cfg.rules
        ]
        return cls(rules, seed=cfg.seed, sleep=sleep)

    @classmethod
    def from_env(cls, env: str = CHAOS_SERVER_ENV) -> "ChaosPolicy | None":
        """Server-side gate: a JSON policy in the env enables injection,
        e.g. ``{"seed": 0, "rules": [{"endpoint": "generate",
        "action": "http_error", "status": 503, "times": 2}]}``."""
        raw = os.environ.get(env, "")
        if not raw:
            return None
        spec = json.loads(raw)
        rules = [
            _Rule(
                endpoint=r.get("endpoint", "*"),
                action=r.get("action", "http_error"),
                probability=float(r.get("probability", 1.0)),
                status=int(r.get("status", 503)),
                delay=float(r.get("delay_seconds", 0.0)),
                times=int(r.get("times", 0)),
            )
            for r in spec.get("rules", [])
        ]
        if not rules:
            return None
        return cls(rules, seed=int(spec.get("seed", 0)))

    # -- runtime --------------------------------------------------------

    def add_rule(
        self,
        endpoint: str = "*",
        action: str = "http_error",
        times: int = 0,
        probability: float = 1.0,
        status: int = 503,
        delay: float = 0.0,
    ) -> None:
        """Arm a rule programmatically (fail-next-N in tests)."""
        self._rules.append(
            _Rule(
                endpoint=endpoint,
                action=action,
                probability=probability,
                status=status,
                delay=delay,
                times=times,
            )
        )

    def decide(self, url_or_path: str) -> ChaosAction | None:
        """The fault (if any) to inject for this request. First matching
        armed rule wins; a ``times``-limited rule disarms after its budget."""
        path = url_or_path.split("?", 1)[0]
        for rule in self._rules:
            if rule.remaining == 0 or not rule.matches(path):
                continue
            if rule.probability < 1.0 and self._rng.random() >= rule.probability:
                continue
            if rule.remaining is not None:
                rule.remaining -= 1
            self.injected += 1
            return _effect(rule)
        return None

    def describe(self) -> str:
        return ", ".join(r.describe() for r in self._rules) or "(no rules)"


def aiohttp_chaos_middleware(policy: ChaosPolicy):
    """Server-side hook: an aiohttp middleware applying ``policy`` to every
    request. Only installed when a policy exists, so the production server
    pays nothing."""
    from aiohttp import web

    @web.middleware
    async def chaos_middleware(request, handler):
        act = policy.decide(request.path)
        if act is None:
            return await handler(request)
        logger.warning("chaos: %s on %s", act.kind, request.path)
        if act.kind == "slow":
            await policy.sleep(act.delay)
            return await handler(request)
        if act.kind == "status":
            if act.delay:
                await policy.sleep(act.delay)
            return web.json_response(
                {"error": "chaos-injected failure"}, status=act.status
            )
        if act.kind == "disconnect":
            # sever mid-stream: the client sees the connection die with no
            # (complete) response on the wire
            if request.transport is not None:
                request.transport.close()
            raise web.HTTPInternalServerError(text="chaos disconnect")
        # drop: hold the request, then sever — the client's own timeout is
        # what surfaces the fault
        await policy.sleep(act.delay or 3600.0)
        if request.transport is not None:
            request.transport.close()
        raise web.HTTPServiceUnavailable(text="chaos drop")

    return chaos_middleware
