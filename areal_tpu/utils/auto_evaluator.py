"""Checkpoint-watching automatic evaluator.

The reference's AutomaticEvaluator (realhf/scheduler/evaluator.py, 348 LoC)
watches the checkpoint directory, launches one offline-eval job per saved
step, and pushes results to wandb. Same design here: poll the saver's output
root for new ``globalstepN`` checkpoints, run a configurable eval command
per checkpoint ({ckpt}/{step} substituted — by default the in-repo offline
eval harness, eval/offline.py), and append results to ``eval_results.jsonl``
under the trial log dir. Runs standalone:

    python -m areal_tpu.utils.auto_evaluator --watch <saves_dir> \
        --cmd "python -m areal_tpu.eval.offline --ckpt {ckpt} ..." --once
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import time

from areal_tpu.utils import logging

logger = logging.getLogger("AutoEvaluator")

_STEP = re.compile(r"globalstep(\d+)$")


class AutomaticEvaluator:
    def __init__(
        self,
        watch_dir: str,
        cmd_template: str,
        output_path: str | None = None,
        poll_interval: float = 10.0,
        timeout: float = 3600.0,
    ):
        self.watch_dir = watch_dir
        self.cmd_template = cmd_template
        self.output_path = output_path or os.path.join(
            watch_dir, "eval_results.jsonl"
        )
        self.poll_interval = poll_interval
        self.timeout = timeout
        self._done: set[str] = set()
        self._load_done()

    def _load_done(self):
        """Resume: don't re-evaluate checkpoints already in the results."""
        if not os.path.isfile(self.output_path):
            return
        with open(self.output_path) as f:
            for line in f:
                try:
                    self._done.add(json.loads(line)["ckpt"])
                except Exception:
                    continue

    def pending_checkpoints(self) -> list[tuple[int, str]]:
        if not os.path.isdir(self.watch_dir):
            return []
        out = []
        for name in os.listdir(self.watch_dir):
            path = os.path.join(self.watch_dir, name)
            m = _STEP.search(name)
            if m is None or not os.path.isdir(path) or path in self._done:
                continue
            # only evaluate checkpoints whose write completed
            if not any(
                os.path.isfile(os.path.join(path, f))
                for f in ("model.safetensors", "config.json")
            ):
                continue
            out.append((int(m.group(1)), path))
        return sorted(out)

    def evaluate_one(self, step: int, ckpt: str) -> dict:
        # literal replacement, not str.format: eval commands legitimately
        # contain braces (inline JSON, jq, shell expansions)
        cmd = self.cmd_template.replace("{ckpt}", ckpt).replace(
            "{step}", str(step)
        )
        logger.info("evaluating step %d: %s", step, cmd)
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                cmd, shell=True, capture_output=True, text=True,
                timeout=self.timeout,
            )
            ok = proc.returncode == 0
            # convention: the eval command prints ONE json line last
            result = None
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    result = json.loads(line)
                    break
                except Exception:
                    continue
        except subprocess.TimeoutExpired:
            ok, result = False, None
        rec = {
            "ckpt": ckpt,
            "global_step": step,
            "ok": ok,
            "result": result,
            "eval_secs": round(time.monotonic() - t0, 2),
        }
        os.makedirs(os.path.dirname(self.output_path), exist_ok=True)
        with open(self.output_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._done.add(ckpt)
        return rec

    def step(self) -> int:
        """Evaluate everything currently pending; returns count evaluated."""
        n = 0
        for step, ckpt in self.pending_checkpoints():
            self.evaluate_one(step, ckpt)
            n += 1
        return n

    def run_forever(self, stop_after: float | None = None):
        t0 = time.monotonic()
        while True:
            self.step()
            if stop_after is not None and time.monotonic() - t0 > stop_after:
                return
            time.sleep(self.poll_interval)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--watch", required=True)
    ap.add_argument("--cmd", required=True)
    ap.add_argument("--out", default=None)
    ap.add_argument("--interval", type=float, default=10.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args(argv)
    ev = AutomaticEvaluator(
        args.watch, args.cmd, output_path=args.out, poll_interval=args.interval
    )
    if args.once:
        ev.step()
    else:
        ev.run_forever()


if __name__ == "__main__":
    main()
