"""Wire encoding for safetensors weight chunks.

The safetensors numpy interface in this image can SAVE ml_dtypes.bfloat16
arrays but cannot LOAD them back (``_TYPES`` has no 'BF16' entry —
``KeyError: 'BF16'`` on the receiving side). Since bf16 is both the
default training dtype and the natural ``WeightUpdateMeta.wire_dtype``,
bf16 leaves ride the wire bit-exactly as uint16 views under a name
marker and are re-viewed on the receiving side. Every other dtype passes
through untouched.
"""

from __future__ import annotations

import numpy as np

#: appended to a leaf's dotted path when its payload is a uint16 view of
#: bfloat16 data ("::" can never appear in a real pytree path)
BF16_MARKER = "::bf16"


def walk_named_leaves(node, prefix: str = ""):
    """Sorted dotted-path iteration over a nested-dict params tree's
    leaves — THE canonical wire order. Every producer of a named chunk
    stream (trainer delta/full pushes, the serving engine's peer-push
    export) must walk in this order: the multi-host delta plan's
    collectives and the per-leaf fingerprints both key on it, so a
    second, subtly different traversal would silently desynchronize
    hosts or digests."""
    for k in sorted(node.keys()):
        v = node[k]
        path = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            yield from walk_named_leaves(v, path)
        else:
            yield path, v


def encode_named(named: dict) -> dict:
    """Prepare a dotted-path -> array chunk for safetensors: contiguous,
    with bfloat16 leaves re-viewed as uint16 under ``path + BF16_MARKER``."""
    out = {}
    for k, v in named.items():
        v = np.ascontiguousarray(v)
        if str(v.dtype) == "bfloat16":
            out[k + BF16_MARKER] = v.view(np.uint16)
        else:
            out[k] = v
    return out


def chunk_digest(named: dict) -> str:
    """Content digest of a dotted-path -> array chunk, stable across the
    encode/decode round trip: hashes (name, canonical dtype, shape, raw
    bytes) in sorted name order, where a bf16 leaf hashes identically
    whether it is still bfloat16 or already a uint16 wire view. Receivers
    recompute this after :func:`decode_named` and compare against the
    digest the sender stamped on the chunk."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for name in sorted(named.keys()):
        v = np.ascontiguousarray(named[name])
        dtype = str(v.dtype)
        if name.endswith(BF16_MARKER):
            name = name[: -len(BF16_MARKER)]
            dtype = "bfloat16"
        elif dtype == "bfloat16":
            v = v.view(np.uint16)
        h.update(name.encode())
        h.update(dtype.encode())
        h.update(repr(tuple(v.shape)).encode())
        h.update(v.tobytes())
    return h.hexdigest()


def decode_named(named: dict) -> dict:
    """Invert :func:`encode_named` after safetensors load (bit-exact)."""
    import ml_dtypes

    out = {}
    for k, v in named.items():
        if k.endswith(BF16_MARKER):
            out[k[: -len(BF16_MARKER)]] = np.asarray(v).view(
                ml_dtypes.bfloat16
            )
        else:
            out[k] = v
    return out
