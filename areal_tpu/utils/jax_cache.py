"""Persistent JAX compilation cache wiring.

A preempted-and-relaunched trainer or generation server (PR 4's recovery
plane) pays full XLA recompile on every restart unless the compilation
cache is pointed at a persistent directory. One knob
(``jax_compilation_cache_dir`` on TrainEngineConfig / JaxGenConfig) routes
here; both the train engine and the generation engine call
:func:`configure_compilation_cache` during startup.

Idempotent and conflict-checked: configuring the same directory twice is a
no-op, configuring two DIFFERENT directories in one process raises (the
cache is process-global — silently switching it mid-run would split the
cache and hide the misconfiguration).
"""

from __future__ import annotations

import os
import threading

from areal_tpu.utils import logging

logger = logging.getLogger("JaxCache")

_LOCK = threading.Lock()
_CONFIGURED_DIR: str | None = None


def configured_dir() -> str | None:
    """The directory the process-global compilation cache was pointed at by
    :func:`configure_compilation_cache` (None = never configured)."""
    with _LOCK:
        return _CONFIGURED_DIR


def configure_compilation_cache(cache_dir: str | None) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True when the cache was (already) configured to ``cache_dir``,
    False when ``cache_dir`` is falsy (knob unset — nothing happens).
    Creating the directory is part of configuring: a relaunch must not
    fail because the first launch never got far enough to create it.
    """
    global _CONFIGURED_DIR
    if not cache_dir:
        return False
    cache_dir = os.path.abspath(cache_dir)
    with _LOCK:
        if _CONFIGURED_DIR is not None:
            if _CONFIGURED_DIR != cache_dir:
                raise RuntimeError(
                    "jax compilation cache already configured at "
                    f"{_CONFIGURED_DIR!r}; refusing to re-point it at "
                    f"{cache_dir!r} (the cache is process-global — set ONE "
                    "jax_compilation_cache_dir per process)"
                )
            return True
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the relaunch-after-preemption win is the
        # SUM over every jitted program, most of which compile in <1s on CPU
        # test shapes but minutes on real models
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except AttributeError:  # older jax without the knob
            pass
        _CONFIGURED_DIR = cache_dir
        logger.info("persistent jax compilation cache at %s", cache_dir)
        return True


def _reset_for_tests() -> None:
    """Drop the process-global configured-dir latch (tests only — the jax
    config itself is NOT reverted)."""
    global _CONFIGURED_DIR
    with _LOCK:
        _CONFIGURED_DIR = None
