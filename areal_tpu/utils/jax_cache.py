"""Persistent JAX compilation cache wiring + compile telemetry.

A preempted-and-relaunched trainer or generation server (PR 4's recovery
plane) pays full XLA recompile on every restart unless the compilation
cache is pointed at a persistent directory. One knob
(``jax_compilation_cache_dir`` on TrainEngineConfig / JaxGenConfig) routes
here; both the train engine and the generation engine call
:func:`configure_compilation_cache` during startup.

Idempotent and conflict-checked: configuring the same directory twice is a
no-op, configuring two DIFFERENT directories in one process raises (the
cache is process-global — silently switching it mid-run would split the
cache and hide the misconfiguration).

Two telemetry layers ride along (the training-plane observatory):

- :func:`install_cache_event_counters` mirrors jax's internal
  ``/jax/compilation_cache/cache_hits``/``cache_misses`` monitoring
  events into the PR 8 metrics registry
  (``areal_jax_compilation_cache_events_total{event=hit|miss}``), so a
  relaunch that silently misses the persistent cache is visible on
  ``/metrics`` and in the StatsLogger registry export.
- :class:`RecompileDetector` counts TRACINGS per jitted function (wrap
  the python callable before handing it to ``jax.jit`` — the wrapper
  body only runs when jax actually traces, i.e. on a jit-cache miss).
  After :meth:`~RecompileDetector.freeze` (the StepTimeline calls it
  once warmup/bucket discovery is over), any further trace is a flagged
  re-trace — except a function's first-ever compile, so late-starting
  paths (evaluation) don't false-positive: one-shot warning per
  function + a counter metric. This is the classic silent
  shape-bucket-miss throughput killer, caught at the moment it happens
  instead of three dashboards later.
"""

from __future__ import annotations

import os
import threading

from areal_tpu.utils import logging

logger = logging.getLogger("JaxCache")

_LOCK = threading.Lock()
_CONFIGURED_DIR: str | None = None


def configured_dir() -> str | None:
    """The directory the process-global compilation cache was pointed at by
    :func:`configure_compilation_cache` (None = never configured)."""
    with _LOCK:
        return _CONFIGURED_DIR


def configure_compilation_cache(cache_dir: str | None) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True when the cache was (already) configured to ``cache_dir``,
    False when ``cache_dir`` is falsy (knob unset — nothing happens).
    Creating the directory is part of configuring: a relaunch must not
    fail because the first launch never got far enough to create it.
    """
    global _CONFIGURED_DIR
    if not cache_dir:
        return False
    cache_dir = os.path.abspath(cache_dir)
    with _LOCK:
        if _CONFIGURED_DIR is not None:
            if _CONFIGURED_DIR != cache_dir:
                raise RuntimeError(
                    "jax compilation cache already configured at "
                    f"{_CONFIGURED_DIR!r}; refusing to re-point it at "
                    f"{cache_dir!r} (the cache is process-global — set ONE "
                    "jax_compilation_cache_dir per process)"
                )
            return True
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the relaunch-after-preemption win is the
        # SUM over every jitted program, most of which compile in <1s on CPU
        # test shapes but minutes on real models
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except AttributeError:  # older jax without the knob
            pass
        _CONFIGURED_DIR = cache_dir
        logger.info("persistent jax compilation cache at %s", cache_dir)
        return True


def _reset_for_tests() -> None:
    """Drop the process-global configured-dir latch (tests only — the jax
    config itself is NOT reverted)."""
    global _CONFIGURED_DIR
    with _LOCK:
        _CONFIGURED_DIR = None


# ---------------------------------------------------------------------------
# Compilation-cache hit/miss counters (jax.monitoring bridge)
# ---------------------------------------------------------------------------

#: jax-internal monitoring event names -> our metric label values
_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "hit",
    "/jax/compilation_cache/cache_misses": "miss",
}

_COUNTERS_INSTALLED = False
# the live counter the (install-once) jax.monitoring listener increments;
# re-installs re-point it so a registry reset (tests) doesn't leave the
# listener feeding a detached orphan
_CACHE_COUNTER_REF: dict = {"counter": None}


def install_cache_event_counters(registry=None) -> bool:
    """Bridge jax's persistent-compilation-cache monitoring events into
    the metrics registry (idempotent — the listener registers once; the
    target counter re-binds on every call). Best-effort: an older/newer
    jax without the monitoring API leaves the counters at zero rather
    than failing startup."""
    global _COUNTERS_INSTALLED
    with _LOCK:
        if registry is None:
            from areal_tpu.utils import metrics

            registry = metrics.DEFAULT_REGISTRY
        _CACHE_COUNTER_REF["counter"] = registry.counter(
            "areal_jax_compilation_cache_events_total",
            "persistent jax compilation cache hits/misses",
            labels=("event",),
        )
        if _COUNTERS_INSTALLED:
            return True
        try:
            import jax.monitoring as _mon

            def _on_event(event: str, **kwargs) -> None:
                label = _CACHE_EVENTS.get(event)
                if label is None:
                    return
                counter = _CACHE_COUNTER_REF["counter"]
                if counter is not None:
                    try:
                        counter.labels(event=label).inc()
                    # fires inside jax.monitoring's compile callback:
                    # logging here could re-enter the listener or spam
                    # once per cache event — silence is deliberate
                    except Exception:  # arealint: disable=swallowed-exception
                        pass

            _mon.register_event_listener(_on_event)
        except Exception:
            logger.info(
                "jax.monitoring unavailable; compilation-cache hit/miss "
                "counters stay at zero"
            )
            return False
        _COUNTERS_INSTALLED = True
        return True


# ---------------------------------------------------------------------------
# Recompile detector
# ---------------------------------------------------------------------------


class RecompileDetector:
    """Count tracings per jitted function; flag re-traces after freeze.

    Wrap the python callable BEFORE ``jax.jit``::

        step = jax.jit(DEFAULT_DETECTOR.wrap("train_engine.grad_step", fn),
                       donate_argnums=(1,))

    The wrapper body executes only when jax traces (a jit-cache miss), so
    steady-state cost is literally zero — no per-call overhead, no
    version-sensitive cache introspection. :meth:`freeze` marks the end
    of warmup (expected compiles: first shapes, bucket discovery); every
    trace after it — except a function's first-ever compile, so paths
    that legitimately start late (evaluation) don't false-positive —
    increments ``areal_jit_retraces_total{fn=...}`` and warns ONCE per
    function name.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # guarded_by: _lock
        self._retraces: dict[str, int] = {}  # guarded_by: _lock
        self._frozen = False
        self._warned: set[str] = set()
        self._registry = registry
        self._counter = None  # lazily created on first retrace

    def wrap(self, name: str, fn):
        def _traced(*args, **kwargs):
            self.note_trace(name)
            return fn(*args, **kwargs)

        return _traced

    def note_trace(self, name: str) -> None:
        warn = False
        with self._lock:
            self._counts[name] = n_traces = self._counts.get(name, 0) + 1
            if not self._frozen:
                return
            if n_traces == 1:
                # first-EVER trace of this function after the freeze: a
                # late first compile (an eval/ref path jitted past
                # warmup), not a bucket miss — its SECOND post-freeze
                # trace is the signal
                return
            self._retraces[name] = self._retraces.get(name, 0) + 1
            if name not in self._warned:
                self._warned.add(name)
                warn = True
            counter = self._retrace_counter()
        try:
            counter.labels(fn=name).inc()
        except Exception:
            logger.debug("retrace counter bump failed", exc_info=True)
        if warn:
            logger.warning(
                "jitted function %r re-traced AFTER warmup (trace #%d): a "
                "shape/dtype/static-arg outside the warmed buckets is "
                "forcing recompiles — the classic silent throughput "
                "killer. Warned once; every further re-trace counts on "
                "areal_jit_retraces_total{fn=%s}.",
                name,
                n_traces,
                name,
            )

    def _retrace_counter(self):
        # called under _lock
        if self._counter is None:
            registry = self._registry
            if registry is None:
                from areal_tpu.utils import metrics

                registry = metrics.DEFAULT_REGISTRY
            self._counter = registry.counter(
                "areal_jit_retraces_total",
                "tracings of a jitted function after the warmup freeze",
                labels=("fn",),
            )
        return self._counter

    def freeze(self) -> None:
        """End of warmup: traces from now on are flagged re-traces."""
        with self._lock:
            self._frozen = True

    @property
    def frozen(self) -> bool:
        with self._lock:
            return self._frozen

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def retraces(self) -> dict[str, int]:
        with self._lock:
            return dict(self._retraces)

    def total_retraces(self) -> int:
        with self._lock:
            return sum(self._retraces.values())

    def reset(self) -> None:
        """Test isolation: drop counts and un-freeze."""
        with self._lock:
            self._counts.clear()
            self._retraces.clear()
            self._warned.clear()
            self._frozen = False
            self._counter = None


DEFAULT_DETECTOR = RecompileDetector()
