"""Single-controller ("controller mode") orchestration.

Parity with the reference's TrainController/RolloutController
(areal/api/controller_api.py:21-455): one controller process owns the
training loop and drives N RPC-hosted engine workers
(scheduler/rpc.EngineRPCServer around a TPUPPOActor / TPULMEngine), sharding
batches with :class:`DistributedBatchMemory`.

TPU-native worker model: the workers are the HOSTS of one
``jax.distributed`` mesh (each runs the same GSPMD program over its device
shard; gradient sync is the mesh's psum, not an RPC concern — the reference
needs a torch process group for the same reason,
areal/controller/train_controller.py). Every model-touching RPC therefore
fans out to ALL workers CONCURRENTLY — each worker enters the same
collective program with its own batch shard. Controller-local work
(advantage pipeline) runs here once, so advantage normalization sees the
GLOBAL batch, matching single-process numerics.

Step anatomy (``train_ppo_step``):
1. version fence — all workers must agree on the weight version;
2. ``chunk_by_ffd`` token-balanced scatter (GRPO groups kept whole);
3. ``compute_logp`` fan-out -> gather ``prox_logp``;
4. controller-local ``compute_advantages`` over the global batch;
5. re-split by the SAME shard sizes -> ``ppo_update`` fan-out;
6. ``step_lr_scheduler`` + version bump fan-out.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any

import numpy as np

from areal_tpu.api.cli_args import PPOActorConfig
from areal_tpu.api.io_struct import SaveLoadMeta, WeightUpdateMeta
from areal_tpu.controller.batch import DistributedBatchMemory
from areal_tpu.scheduler.rpc import EngineRPCClient
from areal_tpu.utils import logging

logger = logging.getLogger("TrainController")


def _meta_kwargs(meta) -> dict:
    import dataclasses

    d = dataclasses.asdict(meta)
    # only JSON-representable fields survive the RPC header; a tokenizer
    # object can't ride the wire (workers load their own from the model
    # path when they need one)
    d = {
        k: v for k, v in d.items()
        if isinstance(v, (str, int, float, bool, type(None)))
    }
    return {"meta": d}


def _merge_stats(
    per_worker: list[dict[str, float]], weights: list[int]
) -> dict[str, float]:
    """Weighted mean over worker stat dicts, keyed by the UNION of keys —
    a stat emitted by one worker only (e.g. a nonfinite-skip counter) must
    not be dropped because worker 0 didn't emit it."""
    keys: list[str] = []
    for p in per_worker:
        for k in p:
            if k not in keys:
                keys.append(k)
    out: dict[str, float] = {}
    for k in keys:
        pairs = [
            (p[k], max(w, 1))
            for p, w in zip(per_worker, weights)
            if k in p
        ]
        tot = sum(w for _, w in pairs)
        out[k] = float(sum(v * w for v, w in pairs) / tot)
    return out


class TrainController:
    """Drives N RPC engine workers through training steps.

    ``clients`` — one :class:`EngineRPCClient` per worker (host) of the
    shared jax.distributed mesh, in process order.
    """

    def __init__(
        self,
        clients: list[EngineRPCClient],
        config: PPOActorConfig | None = None,
    ):
        assert clients, "need at least one worker"
        self.clients = clients
        self.config = config
        self._pool = ThreadPoolExecutor(max_workers=len(clients))
        # controller-local advantage pipeline: PPOActor.compute_advantages
        # never touches the engine, so a detached actor works here and the
        # adv/reward normalization sees the GLOBAL batch (single-process
        # semantics, reference actor.py:72-164)
        self._local_actor = None
        if config is not None:
            from areal_tpu.engine.ppo.actor import PPOActor

            self._local_actor = PPOActor(config, engine=None)

    # -- fan-out plumbing ----------------------------------------------

    def _all(self, method: str, tensors_list=None, **kwargs) -> list[Any]:
        """Call ``method`` on every worker CONCURRENTLY (collective entry:
        a sequential loop would deadlock the mesh)."""
        futs = [
            self._pool.submit(
                c.call,
                method,
                tensors_list[i] if tensors_list is not None else None,
                **kwargs,
            )
            for i, c in enumerate(self.clients)
        ]
        return [f.result() for f in futs]

    # -- engine surface (controller_api.py:207-455 parity) -------------

    def get_version(self) -> int:
        return int(self.clients[0].call("get_version"))

    def set_version(self, version: int):
        self._all("set_version", version=version)

    def step_lr_scheduler(self):
        self._all("step_lr_scheduler")

    def save(self, meta: SaveLoadMeta):
        self._all("save", **_meta_kwargs(meta))

    def load(self, meta: SaveLoadMeta):
        self._all("load", **_meta_kwargs(meta))

    def upload_weights(self, meta: WeightUpdateMeta):
        """All workers join the gather collectives; worker 0 writes."""
        self._all("upload_weights", **_meta_kwargs(meta))

    def version_fence(self) -> int:
        versions = set(self._all("get_version"))
        if len(versions) != 1:
            raise RuntimeError(
                f"workers disagree on weight version: {sorted(versions)}"
            )
        return int(next(iter(versions)))

    # -- training steps -------------------------------------------------

    def train_lm(self, batch: DistributedBatchMemory) -> dict:
        """SFT step: even scatter -> concurrent train_lm -> weighted-mean
        stats (ffd shards are uneven, so means weight by shard rows; keys
        are unioned — a stat one worker alone emits is kept)."""
        shards = batch.chunk(len(self.clients))
        sizes = [len(s) for s in shards]
        stats = self._all("train_lm", tensors_list=[s.to_dict() for s in shards])
        return _merge_stats(stats, sizes)

    def train_ppo_step(
        self, batch: DistributedBatchMemory
    ) -> list[dict[str, float]]:
        """One full GRPO/PPO update across the worker fleet."""
        assert self._local_actor is not None, (
            "construct TrainController with the PPOActorConfig to run PPO"
        )
        cfg = self.config
        n = len(self.clients)
        self.version_fence()

        shards = batch.chunk_by_ffd(cfg.group_size, n)
        sizes = [len(s) for s in shards]
        logger.info("scatter: %s rows per worker", sizes)

        if cfg.recompute_logprob or cfg.use_decoupled_loss:
            outs = self._all(
                "compute_logp_named",
                tensors_list=[s.to_dict() for s in shards],
            )
            for s, o in zip(shards, outs):
                s.data["prox_logp"] = np.asarray(o["logp"])

        # global advantage pipeline on the controller (adv_norm/group norm
        # operate on the whole batch, as in single-process mode)
        full = DistributedBatchMemory.concat(shards)
        data = full.to_dict()
        self._local_actor.compute_advantages(data)
        full = DistributedBatchMemory.from_dict(data)

        update_shards = full.split_sizes(sizes)
        all_stats = self._all(
            "ppo_update", tensors_list=[s.to_dict() for s in update_shards]
        )
        self.step_lr_scheduler()
        # merge the per-worker stats lists pointwise: union of keys (a stat
        # only some workers emit is kept), means weighted by shard rows
        merged: list[dict[str, float]] = []
        for i in range(max(len(s) for s in all_stats)):
            per = [
                (s[i], sizes[w])
                for w, s in enumerate(all_stats)
                if i < len(s)
            ]
            merged.append(
                _merge_stats([p for p, _ in per], [n for _, n in per])
            )
        return merged

    def reconcile_after_recover(
        self, run_state, meta: WeightUpdateMeta | None = None, rollout=None
    ) -> list[str]:
        """Resume-time reconciliation for controller mode: after a restart
        the workers loaded the recovered checkpoint but their in-memory
        version counter starts at 0, and the inference fleet may hold a
        stale (or newer, if the trainer rolled back) weight version. Pins
        every worker to the RunState's weight version, re-uploads the
        recovered weights to the update path, and drives the rollout
        client's version-checked re-push so no resumed rollout is generated
        by mismatched weights. Returns the re-pushed server addresses."""
        version = int(getattr(run_state, "weight_version", run_state or 0))
        self.set_version(version)
        if rollout is None:
            return []
        if (
            meta is not None
            and meta.type == "disk"
            and hasattr(rollout, "reconcile_after_recover")
        ):
            # workers gather + worker 0 writes the recovered weights to the
            # fan-out path (the checkpoint the servers must converge on)
            self.upload_weights(meta)
            return rollout.reconcile_after_recover(meta, version)
        rollout.set_version(version)
        return []

    def update_weights(self, meta: WeightUpdateMeta, rollout=None):
        """Weight push + version bump fan-out (disk path: workers gather,
        worker 0 writes, rollout servers reload)."""
        next_version = self.get_version() + 1
        if meta.type == "disk":
            self.upload_weights(meta)
            if rollout is not None:
                rollout.update_weights(meta)
        else:
            raise NotImplementedError(
                "controller-mode weight updates are disk-based; colocated "
                "device pushes belong to the launcher mode engines"
            )
        self.set_version(next_version)
        if rollout is not None:
            rollout.set_version(next_version)

    def destroy(self):
        self._pool.shutdown(wait=False)
