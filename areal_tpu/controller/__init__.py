from areal_tpu.controller.batch import DistributedBatchMemory  # noqa: F401
