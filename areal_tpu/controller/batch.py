"""Controller-side batch container: chunk / union / concat over row dicts.

Parity with the reference's ``DistributedBatchMemory``
(areal/controller/batch.py:16-366): a padded tensor-dict batch that the
single-controller mode shards across engine workers — even row chunks,
FFD-balanced token chunks (utils/datapack.ffd_allocate), union-by-key, and
concatenation. Arrays are numpy on the controller; engines shard on device.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from areal_tpu.utils.data import TensorDict, concat_padded_tensors
from areal_tpu.utils.datapack import partition_balanced


def _batch_size(data: TensorDict) -> int:
    for v in data.values():
        arr = np.asarray(v)
        if arr.ndim >= 1:
            return arr.shape[0]
    return 0


class DistributedBatchMemory:
    def __init__(self, data: TensorDict):
        self.data = {k: np.asarray(v) for k, v in data.items()}

    @classmethod
    def from_dict(cls, data: TensorDict) -> "DistributedBatchMemory":
        return cls(data)

    def __len__(self) -> int:
        return _batch_size(self.data)

    def __getitem__(self, key: str):
        return self.data[key]

    def keys(self):
        return self.data.keys()

    def _select(self, rows: list[int]) -> "DistributedBatchMemory":
        idx = np.asarray(rows, np.int64)
        bs = len(self)
        out = {}
        for k, v in self.data.items():
            out[k] = v[idx] if v.ndim >= 1 and v.shape[0] == bs else v
        return DistributedBatchMemory(out)

    def chunk(self, n: int) -> list["DistributedBatchMemory"]:
        """Even row split (last chunks one shorter when not divisible)."""
        bs = len(self)
        if n <= 0 or bs < n:
            raise ValueError(f"cannot chunk batch of {bs} rows into {n}")
        splits = np.array_split(np.arange(bs), n)
        return [self._select(list(s)) for s in splits]

    def chunk_by_ffd(self, group_size: int, n: int) -> list["DistributedBatchMemory"]:
        """Token-balanced split keeping ``group_size`` row groups intact
        (GRPO groups must stay on one worker — reference batch.py:55+)."""
        bs = len(self)
        assert bs % group_size == 0, (bs, group_size)
        if "attention_mask" in self.data:
            lens = np.asarray(self.data["attention_mask"]).sum(-1)
        else:
            lens = np.ones(bs, np.int64)
        group_costs = lens.reshape(-1, group_size).sum(-1)
        bins = partition_balanced(group_costs, n)
        out = []
        for b in bins:
            rows = [
                g * group_size + i for g in sorted(b) for i in range(group_size)
            ]
            out.append(self._select(rows))
        return out

    def split_sizes(self, sizes: list[int]) -> list["DistributedBatchMemory"]:
        """Contiguous row split by explicit sizes (the controller re-splits
        a concat of ffd shards back into the same per-worker pieces)."""
        assert sum(sizes) == len(self), (sizes, len(self))
        out, start = [], 0
        for n in sizes:
            out.append(self._select(list(range(start, start + n))))
            start += n
        return out

    def union(self, other: "DistributedBatchMemory") -> "DistributedBatchMemory":
        """Merge per-key: other's keys join this batch (same row count)."""
        if len(other) not in (0, len(self)):
            raise ValueError(f"union row mismatch: {len(self)} vs {len(other)}")
        merged = dict(self.data)
        merged.update(other.data)
        return DistributedBatchMemory(merged)

    @classmethod
    def concat(
        cls, batches: list["DistributedBatchMemory"]
    ) -> "DistributedBatchMemory":
        return cls(concat_padded_tensors([b.data for b in batches]))

    def to_dict(self) -> TensorDict:
        return dict(self.data)

    def __repr__(self) -> str:
        return (
            f"DistributedBatchMemory(rows={len(self)}, "
            f"keys={sorted(self.data)})"
        )
