"""Experiment-config -> worker-set synthesis.

The reference derives its worker fleet from the experiment config
(realhf/api/core/system_api.py:174-220 ``ExperimentScheduling`` /
``TasksGroup`` and each experiment's ``scheduling_setup``): counts and
resource specs for model workers, generation servers, the master, flow to
SLURM/Ray. Here the same derivation is one shared function over the
allocation grammar, consumed by every launcher (slurm, GKE JobSet, local)
and by the controller — previously each launcher re-derived counts
inline.

TPU-native worker model: a "trainer" replica is one HOST of the
jax.distributed train mesh (GSPMD handles intra-host devices; the
reference needs one worker per GPU instead), a "gen_server" replica is
one generation-server process holding tp*pp chips, and the cpu-only
"controller" replica is the reference's auto-added master worker.
"""

from __future__ import annotations

import dataclasses

from areal_tpu.api.alloc_mode import AllocationMode, AllocationType


@dataclasses.dataclass
class ResourceSpec:
    """Per-replica resource ask (the reference's ``Scheduling`` role)."""

    chips: int = 0  # accelerator chips
    cpus: int = 4
    mem_mb: int = 16384


@dataclasses.dataclass
class WorkerGroup:
    """A homogeneous worker set (the reference's ``TasksGroup``)."""

    role: str  # "trainer" | "gen_server" | "controller"
    count: int
    resource: ResourceSpec


@dataclasses.dataclass
class ExperimentPlan:
    groups: list[WorkerGroup]

    def group(self, role: str) -> WorkerGroup:
        for g in self.groups:
            if g.role == role:
                return g
        raise KeyError(role)

    @property
    def n_servers(self) -> int:
        """Generation-server replicas; 1 when the allocation has no
        dedicated server fleet (colocated / train-only: one debug
        server)."""
        try:
            return self.group("gen_server").count
        except KeyError:
            return 1

    @property
    def n_trainer_hosts(self) -> int:
        """Trainer processes (jax.distributed hosts); 1 when the
        allocation has no train section (gen-only / eval)."""
        try:
            return self.group("trainer").count
        except KeyError:
            return 1

    @property
    def total_chips(self) -> int:
        return sum(g.count * g.resource.chips for g in self.groups)


def plan_worker_sets(
    allocation_mode: str,
    chips_per_host: int = 4,
    controller_cpus: int = 4,
    controller_mem_mb: int = 16384,
) -> ExperimentPlan:
    """Worker sets from an allocation string.

    - generation servers: one process per gen DP replica, each holding
      ``gen.tp * gen.pp`` chips (a server IS a tp x pp mesh);
    - trainers: the train submesh's world size split over hosts of
      ``chips_per_host`` chips — one jax.distributed process per host;
    - controller: always one cpu-only replica (the reference auto-adds
      the master worker the same way, system_api.py ExperimentConfig
      ``__post_init__``).

    Colocated allocations (``jaxgen:...|gspmd:...``) produce gen_server
    count 0: the trainer processes host the colocated engine themselves.
    """
    alloc = AllocationMode.from_str(allocation_mode)
    groups: list[WorkerGroup] = []

    # any allocation with a DEDICATED server fleet (decoupled, gen-only,
    # decoupled-eval) gets gen.dp server replicas; colocated serves from
    # the trainer processes themselves
    if alloc.gen is not None and alloc.type_ != AllocationType.COLOCATED:
        groups.append(
            WorkerGroup(
                role="gen_server",
                count=alloc.gen.dp,
                resource=ResourceSpec(chips=alloc.gen.tp * alloc.gen.pp),
            )
        )

    train = alloc.train
    world = train.world_size if train is not None else 0
    if world:
        per_host = min(chips_per_host, world)
        if world % per_host:
            raise ValueError(
                f"train world size {world} does not fill hosts of "
                f"{per_host} chips evenly"
            )
        groups.append(
            WorkerGroup(
                role="trainer",
                count=world // per_host,
                resource=ResourceSpec(chips=per_host),
            )
        )

    groups.append(
        WorkerGroup(
            role="controller",
            count=1,
            resource=ResourceSpec(
                chips=0, cpus=controller_cpus, mem_mb=controller_mem_mb
            ),
        )
    )
    return ExperimentPlan(groups=groups)
