"""Generic worker poll-loop framework (controller-mode fleet management).

The capability of the reference's worker runtime
(realhf/system/worker_base.py: WorkerServer command handlers + status
registry + WorkerControlPanel group requests + heartbeat ``pulse``),
re-hosted on this repo's primitives — aiohttp for the control plane (like
scheduler/rpc.py) and name_resolve for discovery/heartbeats:

- :class:`Worker`: subclass with ``_configure(payload)`` / ``_poll()`` /
  ``_exit_hook()``. ``run()`` announces a control endpoint under
  ``<root>/<worker_name>``, then loops: RUNNING -> ``_poll()`` (returns the
  number of work items done; 0 -> exponential idle backoff), PAUSED/STANDBY
  -> sleep. A heartbeat timestamp rides the same name-resolve record so a
  dead process is detectable without any extra channel.
- :class:`WorkerControl`: controller-side panel — discovery via the
  name-resolve subtree, ``group_request`` fanned out over HTTP, and
  ``pulse()`` marking workers LOST when their heartbeat goes stale.

Commands (POST /cmd): configure | start | pause | resume | exit, plus
GET /status. Unknown commands 404. The control plane is a trusted-cluster
surface, not a public API (same stance as EngineRPCServer).
"""

from __future__ import annotations

import asyncio
import enum
import json
import threading
import time
from typing import Any

from areal_tpu.utils import logging, name_resolve

logger = logging.getLogger("WorkerBase")


class WorkerStatus(str, enum.Enum):
    STANDBY = "STANDBY"  # configured (or fresh), not polling
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    EXITING = "EXITING"
    ERROR = "ERROR"
    LOST = "LOST"  # controller-side verdict: heartbeat went stale


class WorkerException(Exception):
    def __init__(self, worker_name: str, status: WorkerStatus, scenario: str):
        self.worker_name = worker_name
        self.status = status
        super().__init__(
            f"worker {worker_name} is {status.value} during {scenario}"
        )


def _record_key(root: str, name: str) -> str:
    # worker names like "trainer/0" flatten to one key segment so the
    # panel's name <-> key mapping stays bijective
    return f"{root.rstrip('/')}/{name.replace('/', '.')}"


class Worker:
    """Poll-loop worker with an aiohttp control endpoint.

    Subclasses implement ``_poll() -> int`` (work items completed this
    round — 0 engages idle backoff) and optionally ``_configure(payload)``
    / ``_exit_hook()``.
    """

    IDLE_SLEEP_MIN_S = 0.005
    IDLE_SLEEP_MAX_S = 0.5
    HEARTBEAT_S = 2.0

    def __init__(self, name: str, record_root: str = "/areal_tpu/workers",
                 extra_record: dict | None = None):
        self.name = name
        self.record_root = record_root
        self.extra_record = dict(extra_record or {})
        self.status = WorkerStatus.STANDBY
        self._exit_evt = threading.Event()
        self._idle_s = self.IDLE_SLEEP_MIN_S
        self._loop: asyncio.AbstractEventLoop | None = None
        self._runner = None
        self._port: int | None = None
        self._bind_host = "127.0.0.1"
        self._last_beat = 0.0
        self._poll_rounds = 0
        self._work_done = 0

    # ------------------------------------------------------------ subclass
    def _configure(self, payload: dict) -> None:  # noqa: B027
        """Apply controller-sent configuration (optional)."""

    def _poll(self) -> int:
        raise NotImplementedError

    def _exit_hook(self) -> None:  # noqa: B027
        """Cleanup before the loop exits (optional)."""

    # ------------------------------------------------------------- control
    async def _handle_cmd(self, request) -> Any:
        from aiohttp import web

        cmd = request.match_info["cmd"]
        try:
            payload = await request.json()
        except Exception:  # noqa: BLE001 — empty body is fine
            payload = {}
        if cmd == "configure":
            self._configure(payload)
            self.status = WorkerStatus.STANDBY
        elif cmd == "start":
            self.status = WorkerStatus.RUNNING
        elif cmd == "pause":
            if self.status == WorkerStatus.RUNNING:
                self.status = WorkerStatus.PAUSED
        elif cmd == "resume":
            if self.status == WorkerStatus.PAUSED:
                self.status = WorkerStatus.RUNNING
        elif cmd == "exit":
            self.status = WorkerStatus.EXITING
            self._exit_evt.set()
        else:
            return web.json_response({"error": f"unknown cmd {cmd}"},
                                     status=404)
        self._announce()
        return web.json_response({"status": self.status.value})

    async def _handle_status(self, request) -> Any:
        from aiohttp import web

        return web.json_response({
            "status": self.status.value,
            "poll_rounds": self._poll_rounds,
            "work_done": self._work_done,
        })

    def _start_server(self, host: str, port: int) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/cmd/{cmd}", self._handle_cmd)
        app.router.add_get("/status", self._handle_status)
        started = threading.Event()
        actual: list[int] = []

        def _thread():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _up():
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, host, port)
                await site.start()
                self._runner = runner
                actual.append(site._server.sockets[0].getsockname()[1])
                started.set()

            self._loop.run_until_complete(_up())
            self._loop.run_forever()

        threading.Thread(target=_thread, daemon=True,
                         name=f"worker-ctl-{self.name}").start()
        if not started.wait(timeout=30):
            raise RuntimeError("worker control server failed to start")
        self._port = actual[0]
        return self._port

    def _reachable_host(self) -> str:
        # the record must carry an address OTHER hosts can dial: a
        # 0.0.0.0 bind resolves to this host's IP, loopback stays as-is
        # (single-host/test deployments)
        if self._bind_host in ("0.0.0.0", "::", ""):
            from areal_tpu.utils.network import gethostip

            return gethostip()
        return self._bind_host

    def _announce(self):
        self._last_beat = time.time()
        name_resolve.add(
            _record_key(self.record_root, self.name),
            json.dumps({
                **self.extra_record,
                # core fields AFTER the spread: panel addressing and
                # liveness must not be hijackable by a caller-supplied
                # extra_record key. "name" is the name as constructed
                # ("trainer/0") — the record key flattens '/' to '.', so
                # the panel needs it to accept lookups by original name.
                "addr": f"{self._reachable_host()}:{self._port}",
                "name": self.name,
                "status": self.status.value,
                "beat": self._last_beat,
            }),
            replace=True,
        )

    # ----------------------------------------------------------------- run
    def run(self, host: str = "127.0.0.1", port: int = 0) -> None:
        """Serve the control endpoint and poll until told to exit."""
        self._bind_host = host
        self._start_server(host, port)
        self._announce()
        # the heartbeat rides the control-server loop, NOT the poll loop:
        # a long _poll() (a full train step) must not read as a dead worker
        def _beat():
            if not self._exit_evt.is_set():
                self._announce()
                self._loop.call_later(self.HEARTBEAT_S, _beat)

        self._loop.call_soon_threadsafe(
            self._loop.call_later, self.HEARTBEAT_S, _beat
        )
        logger.info("worker %s control endpoint on :%d", self.name, self._port)
        try:
            while not self._exit_evt.is_set():
                if self.status != WorkerStatus.RUNNING:
                    self._exit_evt.wait(0.02)
                    continue
                try:
                    done = int(self._poll())
                except Exception:
                    logger.exception("worker %s poll failed", self.name)
                    self.status = WorkerStatus.ERROR
                    self._announce()
                    raise
                self._poll_rounds += 1
                if done > 0:
                    self._work_done += done
                    self._idle_s = self.IDLE_SLEEP_MIN_S
                else:
                    # nothing to do: exponential backoff caps the idle spin
                    self._exit_evt.wait(self._idle_s)
                    self._idle_s = min(self._idle_s * 2, self.IDLE_SLEEP_MAX_S)
        finally:
            try:
                self._exit_hook()
            finally:
                if self.status != WorkerStatus.ERROR:
                    self.status = WorkerStatus.EXITING
                self._announce()

    def request_exit(self):
        self._exit_evt.set()


class WorkerControl:
    """Controller-side panel over the worker fleet (reference
    WorkerControlPanel.group_request / get_worker_status / pulse)."""

    def __init__(self, record_root: str = "/areal_tpu/workers",
                 heartbeat_timeout: float = 10.0):
        self.record_root = record_root
        self.heartbeat_timeout = heartbeat_timeout

    def worker_records(self) -> dict[str, dict]:
        recs = {}
        try:
            for key in name_resolve.find_subtree(self.record_root):
                try:
                    rec = json.loads(name_resolve.get(key))
                except name_resolve.NameEntryNotFoundError:
                    continue
                # key by the name the Worker was constructed with (the
                # record key flattens '/' to '.'; records from older
                # workers without the field fall back to the flat form)
                recs[rec.get("name") or key.rsplit("/", 1)[-1]] = rec
        except name_resolve.NameEntryNotFoundError:
            pass
        return recs

    def _request(self, addr: str, path: str, timeout: float) -> dict:
        import urllib.request

        req = urllib.request.Request(
            f"http://{addr}{path}", data=b"{}",
            method="POST" if path.startswith("/cmd") else "GET",
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def group_request(self, cmd: str, names: list[str] | None = None,
                      timeout: float = 30.0) -> dict[str, dict]:
        """Send ``cmd`` to every (or the named) worker; name -> response."""
        recs = self.worker_records()
        targets = names if names is not None else sorted(recs)
        out = {}
        for n in targets:
            if n not in recs:
                raise WorkerException(n, WorkerStatus.LOST, f"cmd {cmd}")
            out[n] = self._request(recs[n]["addr"], f"/cmd/{cmd}", timeout)
        return out

    def get_status(self, name: str, timeout: float = 10.0) -> WorkerStatus:
        recs = self.worker_records()
        if name not in recs:
            return WorkerStatus.LOST
        try:
            r = self._request(recs[name]["addr"], "/status", timeout)
            return WorkerStatus(r["status"])
        except Exception:  # noqa: BLE001 — unreachable = lost
            return WorkerStatus.LOST

    def pulse(self) -> dict[str, WorkerStatus]:
        """Heartbeat check over the whole fleet: stale beat -> LOST
        (the reference's failure-detection loop)."""
        now = time.time()
        out = {}
        for n, rec in self.worker_records().items():
            if now - float(rec.get("beat", 0)) > self.heartbeat_timeout:
                out[n] = WorkerStatus.LOST
            else:
                out[n] = WorkerStatus(rec.get("status", "STANDBY"))
        return out

    def wait_all(self, status: WorkerStatus, names: list[str] | None = None,
                 timeout: float = 60.0, interval: float = 0.05) -> None:
        deadline = time.time() + timeout
        while True:
            recs = self.worker_records()
            targets = names if names is not None else sorted(recs)
            if targets and all(
                recs.get(n, {}).get("status") == status.value
                for n in targets
            ):
                return
            if time.time() > deadline:
                raise TimeoutError(
                    f"workers {targets} did not reach {status.value}: "
                    f"{ {n: recs.get(n, {}).get('status') for n in targets} }"
                )
            time.sleep(interval)
