"""RPC engine worker (controller mode).

One process per mesh host: builds the PPO actor engine on this host's
devices, joins ``jax.distributed`` when the fleet spans processes, and
exposes the engine over :class:`EngineRPCServer` for a
:class:`TrainController` to drive (reference: areal/scheduler/rpc launch
path + controller_api.py worker side).

    python -m areal_tpu.controller.worker --config cfg.yaml \
        [--port 0] [--coordinator HOST:PORT --nprocs N --pid I] \
        [--port-file /path]

The chosen port is printed on stdout (and written to --port-file) so the
controller can discover workers started with port 0.
"""

from __future__ import annotations

import argparse
import sys
import threading


def serve(engine, host: str = "0.0.0.0", port: int = 0,
          port_file: str | None = None) -> int:
    from areal_tpu.scheduler.rpc import EngineRPCServer

    server = EngineRPCServer(engine)
    actual = server.start_threaded(host, port)
    print(f"AREAL_WORKER_PORT={actual}", flush=True)
    if port_file:
        with open(port_file, "w") as f:
            f.write(str(actual))
    return actual


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--nprocs", type=int, default=1)
    p.add_argument("--pid", type=int, default=0)
    args, overrides = p.parse_known_args(argv)

    from areal_tpu.parallel import distributed

    if args.coordinator:
        distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nprocs,
            process_id=args.pid,
        )
    else:
        distributed.initialize()

    from areal_tpu.api.alloc_mode import AllocationMode
    from areal_tpu.api.cli_args import GRPOConfig, load_expr_config
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo.actor import TPUPPOActor

    cfg, _ = load_expr_config(["--config", args.config, *overrides], GRPOConfig)
    alloc = AllocationMode.from_str(cfg.allocation_mode)
    actor = TPUPPOActor(cfg.actor)
    actor.create_process_group(alloc.train)
    actor.initialize(
        None,
        FinetuneSpec(
            total_train_epochs=cfg.total_train_epochs,
            dataset_size=cfg.train_dataset.batch_size,  # controller feeds data
            train_batch_size=cfg.train_dataset.batch_size,
        ),
    )
    serve(actor, args.host, args.port, args.port_file)
    threading.Event().wait()  # serve until killed


if __name__ == "__main__":
    main(sys.argv[1:])
