"""RPC engine worker (controller mode).

One process per mesh host: builds the PPO actor engine on this host's
devices, joins ``jax.distributed`` when the fleet spans processes, and
exposes the engine over :class:`EngineRPCServer` for a
:class:`TrainController` to drive (reference: areal/scheduler/rpc launch
path + controller_api.py worker side).

    python -m areal_tpu.controller.worker --config cfg.yaml \
        [--port 0] [--coordinator HOST:PORT --nprocs N --pid I] \
        [--port-file /path]

The chosen port is printed on stdout (and written to --port-file) so the
controller can discover workers started with port 0.
"""

from __future__ import annotations

import argparse
import sys
import threading


def serve(
    engine, host: str = "0.0.0.0", port: int = 0,
    port_file: str | None = None, worker_name: str | None = None,
) -> tuple[int, "threading.Event | None"]:
    """Start the RPC server (+ optional worker-framework announce).
    Returns (port, stop_event) — stop_event fires on a panel "exit"."""
    from areal_tpu.scheduler.rpc import EngineRPCServer

    server = EngineRPCServer(engine)
    actual = server.start_threaded(host, port)
    print(f"AREAL_WORKER_PORT={actual}", flush=True)
    if port_file:
        with open(port_file, "w") as f:
            f.write(str(actual))
    if worker_name:
        # announce under the generic worker framework: heartbeat + status
        # + the RPC address, so WorkerControl.pulse() detects a dead
        # engine worker and group_request("exit") tears it down
        from areal_tpu.controller.worker_base import Worker
        from areal_tpu.utils.network import gethostip

        rpc_host = gethostip() if host in ("0.0.0.0", "::", "") else host
        stop_evt = threading.Event()

        class _EngineWorker(Worker):
            def _poll(self):
                return 0  # the RPC server drives the actual work

            def _exit_hook(self):
                server.stop()
                stop_evt.set()

        w = _EngineWorker(
            worker_name, extra_record={"rpc_addr": f"{rpc_host}:{actual}"}
        )
        # bind the control endpoint on the same interface as the RPC
        # server — the default loopback bind would advertise an address
        # other hosts cannot dial (cross-host group_request/get_status
        # would target 127.0.0.1 on the CALLER's machine)
        threading.Thread(target=lambda: w.run(host=host), daemon=True,
                         name=f"announce-{worker_name}").start()
        return actual, stop_evt
    return actual, None


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--port-file", default=None)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--nprocs", type=int, default=1)
    p.add_argument("--pid", type=int, default=0)
    p.add_argument("--worker-name", default=None,
                   help="announce under the worker framework (heartbeat/status)")
    args, overrides = p.parse_known_args(argv)

    from areal_tpu.parallel import distributed

    if args.coordinator:
        distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nprocs,
            process_id=args.pid,
        )
    else:
        distributed.initialize()

    from areal_tpu.api.alloc_mode import AllocationMode
    from areal_tpu.api.cli_args import GRPOConfig, load_expr_config
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.ppo.actor import TPUPPOActor

    cfg, _ = load_expr_config(["--config", args.config, *overrides], GRPOConfig)
    alloc = AllocationMode.from_str(cfg.allocation_mode)
    actor = TPUPPOActor(cfg.actor)
    actor.create_process_group(alloc.train)
    actor.initialize(
        None,
        FinetuneSpec(
            total_train_epochs=cfg.total_train_epochs,
            dataset_size=cfg.train_dataset.batch_size,  # controller feeds data
            train_batch_size=cfg.train_dataset.batch_size,
        ),
    )
    _, stop_evt = serve(actor, args.host, args.port, args.port_file,
                        worker_name=args.worker_name or f"engine/{args.pid}")
    # serve until killed, or until the worker panel sends "exit"
    (stop_evt or threading.Event()).wait()


if __name__ == "__main__":
    main(sys.argv[1:])
