"""Async reward execution.

Capability parity with the reference's ``areal/api/reward_api.py:37-120``
(``AsyncRewardWrapper``): run synchronous, potentially slow/crashy reward
functions in a shared process pool with timeout and broken-pool recovery, so
reward computation never blocks the rollout event loop.

Reward-service integration: an ASYNC ``reward_fn`` (e.g.
``RewardServiceClient.code_reward_fn()`` — sandboxed execution routed
through the service/pool plane) is awaited directly under the same
timeout discipline, no process pool involved. A timeout or failure is a
0.0 verdict for THAT episode — per-episode failure, never a wedged
rollout plane.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import inspect
import os
from typing import Callable

from areal_tpu.utils import logging

logger = logging.getLogger("reward")

_EXECUTOR: concurrent.futures.ProcessPoolExecutor | None = None
_MAX_WORKERS = int(os.environ.get("AREAL_TPU_REWARD_WORKERS", "4"))


def _get_executor() -> concurrent.futures.ProcessPoolExecutor:
    global _EXECUTOR
    if _EXECUTOR is None:
        _EXECUTOR = concurrent.futures.ProcessPoolExecutor(max_workers=_MAX_WORKERS)
    return _EXECUTOR


def _reset_executor():
    global _EXECUTOR
    if _EXECUTOR is not None:
        _EXECUTOR.shutdown(wait=False, cancel_futures=True)
    _EXECUTOR = None


class AsyncRewardWrapper:
    """Wrap a ``reward_fn(prompt, completion, prompt_ids, completion_ids,
    **data) -> float`` for await-able use from workflows. Sync functions
    run in the shared process pool (or in-process); async functions —
    the reward-service plane's client fns — are awaited directly."""

    def __init__(
        self,
        reward_fn: Callable,
        timeout: float = 60.0,
        in_process: bool = False,
    ):
        self.reward_fn = reward_fn
        self.timeout = timeout
        # in_process avoids pool overhead for trivially-fast rewards and is
        # required for closures that can't pickle.
        self.in_process = in_process

    async def __call__(self, *args, **kwargs) -> float:
        if inspect.iscoroutinefunction(self.reward_fn):
            # service/pool-backed async reward: await it under the same
            # timeout contract; a late or failed reward is this episode's
            # 0.0 verdict, not the rollout plane's problem
            try:
                return float(
                    await asyncio.wait_for(
                        self.reward_fn(*args, **kwargs), timeout=self.timeout
                    )
                )
            except asyncio.CancelledError:
                # unlike the pool path below there is no restart-initiated
                # inner cancel here: a CancelledError can only mean OUR
                # task was cancelled, so it must propagate
                raise
            except asyncio.TimeoutError:
                logger.warning(
                    "Async reward timed out after %.1fs; returning 0.",
                    self.timeout,
                )
                return 0.0
            except Exception:
                logger.warning(
                    "Async reward failed; returning 0.", exc_info=True
                )
                return 0.0
        if self.in_process:
            return float(self.reward_fn(*args, **kwargs))
        loop = asyncio.get_running_loop()
        fut = None
        try:
            fut = loop.run_in_executor(
                _get_executor(),
                functools.partial(self.reward_fn, *args, **kwargs),
            )
            return float(await asyncio.wait_for(fut, timeout=self.timeout))
        except asyncio.CancelledError:
            # wait_for cancels the inner future on outer cancellation too, so
            # fut.cancelled() can't distinguish the cases; a pending task
            # cancellation on *us* (caller cancel) must propagate, while a
            # cancel that originated from a pool restart degrades to 0.0.
            task = asyncio.current_task()
            # Task.cancelling() is 3.11+; on 3.10 the cases cannot be
            # distinguished, so default to PROPAGATING (never swallow a
            # caller's cancellation; a pool-restart cancel propagating is
            # merely noisier, a swallowed abort is a hang)
            cancelling = getattr(task, "cancelling", lambda: 1)
            if task is not None and cancelling() > 0:
                raise
            logger.warning("Reward future cancelled by pool restart; returning 0.")
            return 0.0
        except asyncio.TimeoutError:
            # The worker process is still running the hung reward_fn; restart
            # the pool so timed-out workers don't permanently starve it.
            logger.warning("Reward computation timed out; restarting pool, returning 0.")
            _reset_executor()
            return 0.0
        except concurrent.futures.process.BrokenProcessPool:
            logger.warning("Reward process pool broke; restarting pool.")
            _reset_executor()
            return 0.0
