"""Allocation-mode language: device-count/parallel-layout expressions.

Capability parity with the reference's ``areal/api/alloc_mode.py`` (Lark
grammar at alloc_mode.py:316-358, ``ParallelStrategy`` 5-D dataclass, and
``AllocationMode.from_str``): expressions such as

- ``d4t2``                         — train-only layout (4-way DP × 2-way TP)
- ``gspmd:d4t2c2``                 — explicit train backend
- ``jaxgen:d4t2+gspmd:d2t4``       — disaggregated: inference chips + train chips
- ``jaxgen:d2t2|gspmd:d2t2``       — colocated: same chips serve both roles
- ``jaxgen:d4+eval``               — inference + evaluation-only client
- ``gspmd:(attn:d2c2t2|ffn:e4t2)`` — MoE hybrid attn/ffn layouts (the
  realizable expert fold is the FULL (dp, cp) extent with etp == tp;
  parallel/mesh.py rejects partial folds loudly)

Dim letters: d=data, t=tensor, p=pipeline, c=context(sequence), e=expert.
Reference backend names (sglang, vllm, fsdp, megatron) are accepted as aliases
so reference YAML configs parse unchanged, mapping onto the two TPU backends:
``jaxgen`` (continuous-batching JAX inference engine) and ``gspmd`` (mesh
train engine).

TPU mapping: a ParallelStrategy is realized as a ``jax.sharding.Mesh`` with
axes ("dp", "pp", "cp", "ep", "tp") — see areal_tpu/parallel/mesh.py. The
parser here is a hand-written tokenizer/recursive-descent (no grammar files).
"""

from __future__ import annotations

import dataclasses
import enum
import re

GEN_BACKEND_ALIASES = {"sglang": "jaxgen", "vllm": "jaxgen", "jaxgen": "jaxgen"}
TRAIN_BACKEND_ALIASES = {
    "fsdp": "gspmd",
    "megatron": "gspmd",
    "gspmd": "gspmd",
}

DIM_NAMES = {"d": "dp", "t": "tp", "p": "pp", "c": "cp", "e": "ep"}


class AllocationType(enum.Enum):
    DECOUPLED = "decoupled"  # gen chips + train chips
    COLOCATED = "colocated"  # same chips, both roles
    TRAIN_ONLY = "train_only"
    GEN_ONLY = "gen_only"
    DECOUPLED_EVAL = "decoupled_eval"  # gen + eval client (no trainer)


@dataclasses.dataclass(frozen=True)
class ParallelStrategy:
    """5-D parallel layout (reference: alloc_mode.py:35-203).

    ``ep``/``etp``/``edp`` describe the expert (FFN) sub-layout for MoE; for
    dense models they stay 1. The invariant, matching the reference's MoE
    folding, is dp*cp*tp == edp*ep*etp when a hybrid layout is given (the
    attention and FFN layouts must cover the same chips).
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    cp: int = 1
    ep: int = 1
    etp: int = 1
    edp: int = 1

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{f.name} must be a positive int, got {v}")
        if self.ep > 1 or self.etp > 1 or self.edp > 1:
            attn_world = self.dp * self.cp * self.tp
            ffn_world = self.edp * self.ep * self.etp
            if attn_world != ffn_world:
                raise ValueError(
                    f"attn layout covers {attn_world} chips/stage but ffn layout "
                    f"covers {ffn_world}; they must match"
                )

    @property
    def world_size(self) -> int:
        return self.dp * self.tp * self.pp * self.cp

    def __str__(self) -> str:
        def dims_str(pairs):
            s = "".join(f"{l}{v}" for l, v in pairs if v != 1)
            return s or "d1"

        if self.ep > 1 or self.etp > 1 or self.edp > 1:
            default_etp = self.tp
            default_edp = self.dp * self.cp // self.ep if self.ep > 1 else 1
            if self.etp != default_etp or self.edp != default_edp:
                # non-default expert folding only survives hybrid syntax
                attn = dims_str(
                    [("d", self.dp), ("c", self.cp), ("t", self.tp), ("p", self.pp)]
                )
                ffn = dims_str(
                    [("d", self.edp), ("e", self.ep), ("t", self.etp), ("p", self.pp)]
                )
                return f"(attn:{attn}|ffn:{ffn})"
        return dims_str(
            [
                ("d", self.dp),
                ("t", self.tp),
                ("p", self.pp),
                ("c", self.cp),
                ("e", self.ep),
            ]
        )


@dataclasses.dataclass
class AllocationMode:
    type_: AllocationType
    gen_backend: str | None = None
    gen: ParallelStrategy | None = None
    train_backend: str | None = None
    train: ParallelStrategy | None = None

    @property
    def gen_world_size(self) -> int:
        return self.gen.world_size if self.gen else 0

    @property
    def train_world_size(self) -> int:
        return self.train.world_size if self.train else 0

    @property
    def total_world_size(self) -> int:
        if self.type_ == AllocationType.COLOCATED:
            return max(self.gen_world_size, self.train_world_size)
        return self.gen_world_size + self.train_world_size

    # ------------------------- parsing -------------------------
    @classmethod
    def from_str(cls, s: str) -> "AllocationMode":
        s = s.strip().replace(" ", "")
        if not s:
            raise ValueError("Empty allocation mode")
        # legacy dot form: 'sglang.d4t2' (reference grammar's legacy_inf_para)
        all_backends = set(GEN_BACKEND_ALIASES) | set(TRAIN_BACKEND_ALIASES)
        s = re.sub(
            rf"(^|[+|(])({'|'.join(sorted(all_backends))})\.",
            lambda m: m.group(1) + m.group(2) + ":",
            s,
        )
        # decoupled: '+' at top level
        plus_parts = _split_top(s, "+")
        if len(plus_parts) == 2:
            left, right = plus_parts
            if right in ("eval", "cpu"):  # 'cpu' = reference's eval alias
                backend, strat = _parse_role(left, gen=True)
                return cls(AllocationType.DECOUPLED_EVAL, backend, strat)
            gb, gs = _parse_role(left, gen=True)
            tb, ts = _parse_role(right, gen=False)
            return cls(AllocationType.DECOUPLED, gb, gs, tb, ts)
        if len(plus_parts) > 2:
            raise ValueError(f"At most one '+' allowed: {s}")
        bar_parts = _split_top(s, "|")
        if len(bar_parts) == 2:
            gb, gs = _parse_role(bar_parts[0], gen=True)
            tb, ts = _parse_role(bar_parts[1], gen=False)
            if gs.world_size != ts.world_size:
                raise ValueError(
                    f"Colocated roles must cover the same chips: "
                    f"{gs.world_size} vs {ts.world_size}"
                )
            return cls(AllocationType.COLOCATED, gb, gs, tb, ts)
        if len(bar_parts) > 2:
            raise ValueError(f"At most one top-level '|' allowed: {s}")
        # single role
        if ":" in s:
            backend = s.split(":", 1)[0]
            if backend in GEN_BACKEND_ALIASES:
                gb, gs = _parse_role(s, gen=True)
                return cls(AllocationType.GEN_ONLY, gb, gs)
        tb, ts = _parse_role(s, gen=False)
        return cls(AllocationType.TRAIN_ONLY, train_backend=tb, train=ts)


def _split_top(s: str, sep: str) -> list[str]:
    """Split on ``sep`` outside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                raise ValueError(f"Unbalanced ')' in {s!r}")
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if depth != 0:
        raise ValueError(f"Unbalanced '(' in {s!r}")
    parts.append("".join(cur))
    return parts


_DIM_RE = re.compile(r"([dtpce])(\d+)")


def _parse_dims(s: str) -> dict[str, int]:
    pos = 0
    dims: dict[str, int] = {}
    while pos < len(s):
        m = _DIM_RE.match(s, pos)
        if not m:
            raise ValueError(f"Bad parallel spec at {s[pos:]!r} in {s!r}")
        letter, value = m.group(1), int(m.group(2))
        name = DIM_NAMES[letter]
        if name in dims:
            raise ValueError(f"Duplicate dim {letter!r} in {s!r}")
        dims[name] = value
        pos = m.end()
    if not dims:
        raise ValueError(f"Empty parallel spec: {s!r}")
    return dims


def _parse_parallel(s: str) -> ParallelStrategy:
    """Parse either plain dims or a MoE hybrid '(attn:...|ffn:...)'."""
    if s.startswith("("):
        if not s.endswith(")"):
            raise ValueError(f"Unbalanced hybrid spec: {s!r}")
        inner = s[1:-1]
        halves = _split_top(inner, "|")
        if len(halves) != 2:
            raise ValueError(f"Hybrid spec needs 'attn:...|ffn:...': {s!r}")
        spec: dict[str, dict[str, int]] = {}
        for half in halves:
            if ":" not in half:
                raise ValueError(f"Hybrid half missing role: {half!r}")
            role, dims_s = half.split(":", 1)
            if role not in ("attn", "ffn"):
                raise ValueError(f"Hybrid role must be attn|ffn: {role!r}")
            spec[role] = _parse_dims(dims_s)
        if "attn" not in spec or "ffn" not in spec:
            raise ValueError(f"Hybrid spec needs both attn and ffn: {s!r}")
        attn, ffn = spec["attn"], spec["ffn"]
        if "ep" in attn:
            raise ValueError("attn layout cannot have an expert dim")
        if attn.get("pp", 1) != ffn.get("pp", 1):
            raise ValueError("attn and ffn pp must match")
        return ParallelStrategy(
            dp=attn.get("dp", 1),
            tp=attn.get("tp", 1),
            pp=attn.get("pp", 1),
            cp=attn.get("cp", 1),
            ep=ffn.get("ep", 1),
            etp=ffn.get("tp", 1),
            edp=ffn.get("dp", 1),
        )
    dims = _parse_dims(s)
    return ParallelStrategy(
        dp=dims.get("dp", 1),
        tp=dims.get("tp", 1),
        pp=dims.get("pp", 1),
        cp=dims.get("cp", 1),
        ep=dims.get("ep", 1),
        etp=dims.get("tp", 1) if dims.get("ep", 1) > 1 else 1,
        edp=(
            dims.get("dp", 1) * dims.get("cp", 1) // dims.get("ep", 1)
            if dims.get("ep", 1) > 1
            else 1
        ),
    )


def _parse_role(s: str, gen: bool) -> tuple[str, ParallelStrategy]:
    aliases = GEN_BACKEND_ALIASES if gen else TRAIN_BACKEND_ALIASES
    default = "jaxgen" if gen else "gspmd"
    if ":" in s and not s.startswith("("):
        backend, rest = s.split(":", 1)
        if backend not in aliases:
            raise ValueError(
                f"Unknown {'gen' if gen else 'train'} backend {backend!r} "
                f"(known: {sorted(aliases)})"
            )
        return aliases[backend], _parse_parallel(rest)
    return default, _parse_parallel(s)
