"""Engine ABCs: TrainEngine and InferenceEngine.

Capability parity with the reference's ``areal/api/engine_api.py`` (TrainEngine
at engine_api.py:40, InferenceEngine at :347). The method surface is kept so
algorithm code written against the reference maps 1:1; semantics are
TPU-native (params are jax pytrees on a mesh, not torch modules).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, TYPE_CHECKING

from areal_tpu.api.io_struct import (
    FinetuneSpec,
    ModelRequest,
    ModelResponse,
    SaveLoadMeta,
    WeightUpdateMeta,
)

if TYPE_CHECKING:
    from areal_tpu.api.workflow_api import RolloutWorkflow

TensorDict = dict[str, Any]


class TrainEngine(abc.ABC):
    """A sharded trainable model + optimizer on a device mesh."""

    def initialize(self, addr: str | None, ft_spec: FinetuneSpec | None, **kwargs):
        raise NotImplementedError()

    def destroy(self):
        pass

    @property
    def data_parallel_size(self) -> int:
        raise NotImplementedError()

    def current_data_parallel_head(self) -> int:
        return 0

    def is_data_parallel_head(self) -> bool:
        """Single-controller JAX: the controller process is always the head."""
        return True

    def train(self, mode: bool = True):
        return self

    def get_version(self) -> int:
        raise NotImplementedError()

    def set_version(self, version: int):
        raise NotImplementedError()

    def train_batch(
        self,
        input_: TensorDict,
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> dict[str, float]:
        """Forward+backward+step over microbatches of one batch.

        ``loss_fn(logits, mb) -> scalar loss`` (sum-reduced over tokens);
        ``loss_weight_fn(mb) -> float`` gives each microbatch's weight (e.g.
        token count); the global normalizer is the sum over all microbatches,
        matching the reference's loss scaling (fsdp_engine.py:499-606).
        """
        raise NotImplementedError()

    def eval_batch(
        self,
        input_: TensorDict,
        loss_fn: Callable,
        loss_weight_fn: Callable,
    ) -> float | None:
        raise NotImplementedError()

    def forward(
        self,
        input_: TensorDict,
        output_seqlens: list[int] | None = None,
        post_hook: Callable | None = None,
        aggregate_fn: Callable = None,
    ) -> Any:
        """Microbatched inference forward; ``post_hook(logits, mb) -> out``
        runs on-device per microbatch; results re-ordered to input order."""
        raise NotImplementedError()

    def step_lr_scheduler(self):
        raise NotImplementedError()

    def save(self, meta: SaveLoadMeta):
        raise NotImplementedError()

    def load(self, meta: SaveLoadMeta):
        raise NotImplementedError()

    def upload_weights(self, meta: WeightUpdateMeta):
        """Push current weights toward inference engines (disk or device)."""
        raise NotImplementedError()

    def connect_engine(self, engine: "InferenceEngine", meta: WeightUpdateMeta):
        """Pair with a rollout engine for weight updates + data redistribution
        (reference: fsdp_engine.py:437-455)."""
        raise NotImplementedError()


class InferenceEngine(abc.ABC):
    """Client to (possibly remote) generation service(s)."""

    def initialize(self, addr: str | None = None, **kwargs):
        raise NotImplementedError()

    def destroy(self):
        pass

    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        raise NotImplementedError()

    def generate(self, req: ModelRequest) -> ModelResponse:
        raise NotImplementedError()

    def update_weights(self, meta: WeightUpdateMeta):
        raise NotImplementedError()

    def get_version(self) -> int:
        raise NotImplementedError()

    def set_version(self, version: int):
        raise NotImplementedError()

    def submit(
        self,
        data: TensorDict,
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
    ) -> None:
        raise NotImplementedError()

    def wait(self, count: int, timeout: float | None = None) -> TensorDict:
        raise NotImplementedError()

    def rollout_batch(
        self,
        data: list[TensorDict],
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
    ) -> TensorDict:
        raise NotImplementedError()

    def prepare_batch(
        self,
        dataloader,
        workflow: "RolloutWorkflow | None" = None,
        workflow_builder: Callable | None = None,
    ) -> TensorDict:
        raise NotImplementedError()

    def pause(self):
        """Pause accepting/issuing generation (during weight update)."""
        raise NotImplementedError()

    def resume(self):
        raise NotImplementedError()
