"""Async tool-execution environment API (reference: areal/api/env_api.py:5-28).

Agentic workflows (tool-integrated reasoning, search agents) hold an
``Environment`` per episode: initialize, list tools, execute calls with a
timeout, close. Concrete example: examples/tir's python-executor environment.
"""

from __future__ import annotations

import abc
from typing import Any


class Environment(abc.ABC):
    async def ainitialize(self) -> None:
        """Acquire resources (sandboxes, browsers, connections)."""

    async def aclose(self) -> None:
        """Release resources."""

    @abc.abstractmethod
    async def alist_tools(self) -> list[dict[str, Any]]:
        """Tool schemas (OpenAI function-call format)."""
        ...

    @abc.abstractmethod
    async def aexecute(
        self, tool_name: str, arguments: dict[str, Any], timeout: float = 30.0
    ) -> tuple[str, bool]:
        """Run one tool call. Returns (observation_text, success)."""
        ...

    async def __aenter__(self) -> "Environment":
        await self.ainitialize()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.aclose()
