"""Config system: dataclasses + YAML file + dotted CLI overrides.

Capability parity with the reference's ``areal/api/cli_args.py`` (SURVEY §2.4):
the same config surface (GenerationHyperparameters, OptimizerConfig,
TrainEngineConfig, PPOActorConfig, InferenceEngineConfig, saver/eval/recover
timers, DatasetConfig, launcher configs, BaseExperimentConfig and the
SFT/GRPO/PPO experiment types) and the same loading convention
(``--config file.yaml key=value ...``). The reference leans on OmegaConf;
here structured merge/coercion is implemented directly (no omegaconf in the
TPU image).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import types
import typing
from dataclasses import dataclass, field
from typing import Any

import yaml

# re-exported so the auto-generated cli_reference documents the perf-
# regression sentinel's knobs next to every other config (the module is
# stdlib-only by contract — bench.py's parent process must not pull jax)
from areal_tpu.bench.regression import BenchSentinelConfig  # noqa: F401
from areal_tpu.utils.name_resolve import NameResolveConfig

# --------------------------------------------------------------------------
# Structured merge machinery (OmegaConf replacement)
# --------------------------------------------------------------------------


def _is_dataclass_type(tp) -> bool:
    return dataclasses.is_dataclass(tp) and isinstance(tp, type)


def _coerce(value: Any, tp: Any) -> Any:
    origin = typing.get_origin(tp)
    if tp is Any or tp is None:
        return value
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if value is None:
            return None
        dc_error: Exception | None = None
        for a in args:
            try:
                return _coerce(value, a)
            except (TypeError, ValueError) as e:
                # keep the precise unknown-key error from a dataclass arm
                if _is_dataclass_type(a) and isinstance(value, dict):
                    dc_error = dc_error or e
                continue
        if dc_error is not None:
            raise dc_error
        raise TypeError(f"Cannot coerce {value!r} to {tp}")
    if _is_dataclass_type(tp):
        if isinstance(value, tp):
            return value
        if isinstance(value, dict):
            return from_dict(tp, value)
        raise TypeError(f"Cannot coerce {value!r} to {tp}")
    if origin in (list, tuple):
        args = typing.get_args(tp)
        elem = args[0] if args else Any
        if isinstance(value, str):
            value = [v for v in value.split(",") if v]
        seq = [_coerce(v, elem) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        return dict(value)
    if tp is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            if value.lower() in ("true", "1", "yes"):
                return True
            if value.lower() in ("false", "0", "no"):
                return False
            raise ValueError(f"Cannot parse bool: {value!r}")
        return bool(value)
    if tp is int:
        if isinstance(value, bool):
            raise TypeError("bool is not int")
        return int(value)
    if tp is float:
        return float(value)
    if tp is str:
        return str(value)
    return value


# --------------------------------------------------------------------------
# Reference-YAML compatibility (cli audit, PARITY.md "cli_args audit" table):
# per-dataclass key ALIASES (reference key -> our dotted key) and
# ACCEPTED-BUT-IGNORED keys (warned once, value dropped — knobs whose role
# doesn't exist in the TPU design). Anything not listed and not a field
# still raises, preserving typo-catching.
# --------------------------------------------------------------------------

# looked up over cls.__mro__, so PPOActorConfig/PPOCriticConfig inherit the
# TrainEngineConfig entries (subclass tables add to — and override — them)
_KEY_ALIASES: dict[str, dict[str, str]] = {
    "TrainEngineConfig": {
        "virtual_pipeline_parallel_size": "backend.vpp",
        "dtype": "backend.param_dtype",
        "grad_reduce_dtype": "backend.grad_acc_dtype",
        "gradient_checkpointing": "backend.remat",
        "lora_rank": "lora.rank",
        "lora_alpha": "lora.alpha",
        "target_modules": "lora.target_modules",
    },
    "OptimizerConfig": {
        "lr_scheduler_type": "lr_scheduler.type",
        "warmup_steps_proportion": "lr_scheduler.warmup_steps_proportion",
        "min_lr_ratio": "lr_scheduler.min_lr_ratio",
        "offload": "offload_optimizer_state",
    },
    "ClusterSpecConfig": {"n_gpus_per_node": "n_chips_per_host"},
}

_IGNORED_KEYS: dict[str, dict[str, str]] = {
    # class -> {key: why it has no TPU counterpart}; merged over __mro__
    "TrainEngineConfig": {
        "pad_to_maximum": "backend.pad_mb_to_multiple buckets instead",
        "disable_dropout": "the TPU models carry no dropout at all",
        "weight_update_mode": "WeightUpdateMeta chooses disk/device/http/lora",
        "fsdp": "one GSPMD backend replaces the FSDP engine config",
        "megatron": "one GSPMD backend replaces the Megatron engine config",
        "peft_type": "lora is the only PEFT type (matching the reference)",
        "use_lora": "presence of the lora section enables adapters",
        "is_critic": "criticness rides PPOCriticConfig / model config",
    },
    "PPOActorConfig": {
        "log_agent_stats": "agent stats ride the stats_tracker scopes",
        "log_agent_stats_keys": "agent stats ride the stats_tracker scopes",
    },
    "OptimizerConfig": {
        "initial_loss_scale": "bf16 training needs no fp16 loss scaling",
        "min_loss_scale": "bf16 training needs no fp16 loss scaling",
        "loss_scale_window": "bf16 training needs no fp16 loss scaling",
        "hysteresis": "bf16 training needs no fp16 loss scaling",
    },
    "GenerationHyperparameters": {
        "max_tokens": "per-request totals derive from max_new_tokens + "
        "prompt length; the server enforces max_seq_len",
    },
    "StatsLoggerConfig": {
        "swanlab": "no swanlab in the TPU image (wandb/tensorboard do)",
    },
    "BaseExperimentConfig": {
        "scheduler": "launcher/slurm sections cover worker scheduling",
    },
    "SFTConfig": {"scheduler": "launcher/slurm sections cover scheduling"},
    "GRPOConfig": {"scheduler": "launcher/slurm sections cover scheduling"},
    "PPOConfig": {"scheduler": "launcher/slurm sections cover scheduling"},
    "RWConfig": {"scheduler": "launcher/slurm sections cover scheduling"},
}

# reference sglang/vllm server sections -> JaxGenConfig ("server") fields;
# unmapped engine-tuning keys are dropped with one summary warning
_SERVER_SECTION_MAP = {
    "model_path": "model_path",
    "dtype": "dtype",
    "random_seed": "random_seed",
    "skip_tokenizer_init": "skip_tokenizer_init",
    "context_length": "max_seq_len",
    "max_running_requests": "max_batch_size",
    "mem_fraction_static": "hbm_utilization",
    "gpu_memory_utilization": "hbm_utilization",
    "page_size": "page_size",
}

_warned_keys: set = set()


def _warn_once(msg: str):
    if msg not in _warned_keys:
        _warned_keys.add(msg)
        import warnings

        warnings.warn(msg, stacklevel=3)


def _set_dotted_default(d: dict, dotted_key: str, value: Any, src: str):
    """Like _set_dotted but an explicitly-set canonical key WINS over the
    reference alias (warned), matching the sglang-section setdefault
    precedence."""
    parts = dotted_key.split(".")
    cur = d
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
        if not isinstance(cur, dict):
            raise ValueError(f"Cannot override non-dict path {dotted_key}")
    if parts[-1] in cur:
        _warn_once(
            f"both reference key {src!r} and canonical {dotted_key!r} are "
            f"set; the canonical value wins"
        )
        return
    cur[parts[-1]] = value


def _apply_compat(cls, data: dict) -> dict:
    """Reference-YAML key compatibility: aliases move values to our fields,
    ignored keys drop with a one-time warning, sglang/vllm server sections
    map onto the in-repo JAX server config. Tables merge over ``__mro__``
    (base-class entries apply to subclasses)."""
    name = cls.__name__
    aliases: dict = {}
    ignored: dict = {}
    for klass in reversed(getattr(cls, "__mro__", [cls])):
        aliases.update(_KEY_ALIASES.get(klass.__name__, {}))
        ignored.update(_IGNORED_KEYS.get(klass.__name__, {}))
    if not aliases and not ignored and name not in (
        "GRPOConfig", "PPOConfig", "SFTConfig", "RWConfig",
        "BaseExperimentConfig",
    ):
        return data
    data = dict(data)
    use_lora = data.get("use_lora")
    for key in list(data):
        if key in aliases:
            _set_dotted_default(data, aliases[key], data.pop(key), key)
        elif key in ignored:
            _warn_once(
                f"{name}.{key} is accepted but ignored on TPU: {ignored[key]}"
            )
            data.pop(key)
        elif key in ("sglang", "vllm") and "server" in {
            f.name for f in dataclasses.fields(cls)
        }:
            section = data.pop(key) or {}
            dropped = []
            for k, v in section.items():
                if k in _SERVER_SECTION_MAP:
                    data.setdefault("server", {})
                    if isinstance(data["server"], dict):
                        data["server"].setdefault(_SERVER_SECTION_MAP[k], v)
                else:
                    dropped.append(k)
            if dropped:
                _warn_once(
                    f"{name}.{key}: {len(dropped)} engine-tuning keys have "
                    f"no JAX-server counterpart and were ignored: "
                    f"{sorted(dropped)}"
                )
    if use_lora is False:
        # reference YAML disabled LoRA: the lora_* aliases must not enable it
        data.pop("lora", None)
    return data


def from_dict(cls, data: dict[str, Any]):
    """Build dataclass ``cls`` from a nested dict with type coercion; unknown
    keys raise (catching config typos, like OmegaConf structured mode).
    Reference-YAML keys that have a mapped or intentionally-dropped role are
    translated first (``_apply_compat``)."""
    if data is None:
        data = {}
    data = _apply_compat(cls, data)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(f"Unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for name, f in fields.items():
        if name in data:
            kwargs[name] = _coerce(data[name], hints.get(name, Any))
    return cls(**kwargs)


def to_dict(cfg) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def _set_dotted(d: dict, dotted_key: str, value: Any):
    parts = dotted_key.split(".")
    cur = d
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
        if not isinstance(cur, dict):
            raise ValueError(f"Cannot override non-dict path {dotted_key}")
    cur[parts[-1]] = value


def _parse_override_value(s: str) -> Any:
    try:
        return yaml.safe_load(s)
    except yaml.YAMLError:
        return s


def parse_cli_args(argv: list[str] | None = None):
    """``--config file.yaml key=value ...`` -> (merged dict, config path).

    Reference behavior: areal/api/cli_args.py:1247.
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument("--config", type=str, default=None)
    args, overrides = parser.parse_known_args(argv)
    data: dict[str, Any] = {}
    if args.config:
        with open(args.config) as f:
            data = yaml.safe_load(f) or {}
    for ov in overrides:
        if ov.startswith("--"):
            raise ValueError(
                f"Unknown flag {ov!r}: overrides use plain 'key=value' syntax "
                "(no leading dashes)"
            )
        if "=" not in ov:
            raise ValueError(f"Override must be key=value, got {ov!r}")
        k, v = ov.split("=", 1)
        _set_dotted(data, k, _parse_override_value(v))
    return data, args.config


def load_expr_config(argv: list[str] | None, cls):
    """Load an experiment config of dataclass type ``cls`` and apply its
    name-resolve configuration (reference: areal/api/cli_args.py:1280-1286)."""
    from areal_tpu.utils import name_resolve as _nr

    data, config_path = parse_cli_args(argv)
    cfg = from_dict(cls, data)
    cluster = getattr(cfg, "cluster", None)
    if cluster is not None and getattr(cluster, "name_resolve", None) is not None:
        _nr.reconfigure(cluster.name_resolve)
    return cfg, config_path


# --------------------------------------------------------------------------
# Leaf configs
# --------------------------------------------------------------------------


@dataclass
class NormConfig:
    """Advantage/value normalization spec (reference cli_args.py:24)."""

    mean_level: str = "batch"  # batch | group | none
    std_level: str = "batch"  # batch | group | none
    group_size: int = 1
    eps: float = 1e-5
    # RLOO-style leave-one-out mean: each sample's baseline excludes itself
    mean_leave1out: bool = False
    # Bessel-corrected std (n-1 denominator)
    std_unbiased: bool = False


@dataclass
class MicroBatchSpec:
    """Microbatch splitting spec (reference cli_args.py:63)."""

    n_mbs: int = 1
    max_tokens_per_mb: int = 1 << 30  # effectively unbounded by default
    granularity: int = 1


@dataclass
class GenerationHyperparameters:
    """Sampling params for rollout (reference cli_args.py:98)."""

    n_samples: int = 1
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    stop_token_ids: list[int] = field(default_factory=list)
    stop: list[str] = field(default_factory=list)
    frequency_penalty: float = 0.0

    def new(self, **kwargs) -> "GenerationHyperparameters":
        return dataclasses.replace(self, **kwargs)


@dataclass
class LRSchedulerConfig:
    type: str = "constant"  # constant | linear | cosine
    warmup_steps_proportion: float = 0.001
    min_lr_ratio: float = 0.0


@dataclass
class OptimizerConfig:
    """Optax-backed optimizer config (reference cli_args.py:161)."""

    type: str = "adamw"
    lr: float = 2e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    gradient_clipping: float = 1.0
    lr_scheduler: LRSchedulerConfig = field(default_factory=LRSchedulerConfig)
    offload_optimizer_state: bool = False


@dataclass
class EngineBackendConfig:
    """GSPMD train-backend knobs (replaces the reference's FSDPEngineConfig /
    MegatronEngineConfig pair, cli_args.py:242,274 — one JAX backend)."""

    remat: bool = True  # jax.checkpoint each block (activation remat)
    # one of models/lm.py _REMAT_POLICIES: nothing_saveable | dots_saveable
    # | dots_with_no_batch_dims_saveable
    remat_policy: str = "nothing_saveable"
    param_dtype: str = "bfloat16"
    # "" = follow param_dtype; set explicitly (e.g. "bfloat16" with
    # param_dtype="float32") for mixed-precision forward/backward — params
    # are cast at the top of each compute (train_engine._cast_for_compute)
    compute_dtype: str = ""
    optimizer_dtype: str = "float32"  # adam mu AND nu storage dtype
    grad_acc_dtype: str = "float32"  # microbatch gradient accumulator dtype
    fsdp: bool = True  # shard params/optimizer over the dp axis (ZeRO-3-like)
    donate_params: bool = True
    pad_mb_to_multiple: int = 128  # static-shape bucketing for XLA
    # > 0 fuses LM head + log-softmax into token chunks of this size
    # (models/lm.forward_fused_logp): full [T, V] logits are never
    # materialized, which long-context training needs (32k x 152k fp32
    # logits = 19.5GB). 0 = classic full-logits loss. LM/PPO-actor losses
    # only; ignored for critics/RM and under pipeline parallelism.
    loss_chunk_size: int = 0
    # pipeline schedule (pp > 1): "gpipe" = one forward pipeline + AD
    # (stores O(M) stage activations); "1f1b" = hand-rolled interleaved
    # one-forward-one-backward (parallel/pipeline.pipeline_train_step_1f1b),
    # O(pp) live activations — feed more microbatches per step for the same
    # memory, shrinking the bubble. LoRA engines fall back to gpipe.
    pp_schedule: str = "gpipe"
    # virtual pipeline (interleaved) stages per pp device — the Megatron
    # virtual_pipeline_parallel_size capability (reference
    # alloc_mode.py:216-241): each device owns vpp non-contiguous layer
    # chunks, cutting the pipeline bubble by vpp x
    # (parallel/pipeline.pipeline_hidden_interleaved). gpipe schedule only;
    # needs num_hidden_layers % (pp * vpp) == 0.
    vpp: int = 1


@dataclass
class TrainEngineConfig:
    """Reference cli_args.py:317."""

    experiment_name: str = ""
    trial_name: str = ""
    path: str = ""  # HF model path or name
    init_from_scratch: bool = False
    attn_impl: str = "auto"  # auto | pallas | xla
    mb_spec: MicroBatchSpec = field(default_factory=MicroBatchSpec)
    optimizer: OptimizerConfig | None = field(default_factory=OptimizerConfig)
    backend: EngineBackendConfig = field(default_factory=EngineBackendConfig)
    lora: "LoRAConfig | None" = None
    # persistent JAX compilation cache directory (trainer side): a relaunch
    # after preemption (PR 4) reloads compiled train-step executables
    # instead of paying full recompile. None = off.
    jax_compilation_cache_dir: str | None = None


@dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    dropout: float = 0.0
    target_modules: list[str] = field(
        default_factory=lambda: ["q_proj", "k_proj", "v_proj", "o_proj"]
    )


@dataclass
class PPOActorConfig(TrainEngineConfig):
    """PPO/GRPO actor knobs (reference cli_args.py:392)."""

    group_size: int = 1
    ppo_n_minibatches: int = 4
    eps_clip: float = 0.2
    eps_clip_higher: float | None = None  # DAPO clip-higher
    c_clip: float | None = None  # dual clip
    temperature: float = 1.0
    # reward shaping
    # full reward-normalization spec (reference PPOActorConfig.reward_norm);
    # group_reward_norm is the boolean shorthand for group/group
    reward_norm: NormConfig | None = None
    group_reward_norm: bool = False
    reward_scaling: float = 1.0
    reward_bias: float = 0.0
    reward_clip: float = 20.0
    overlong_reward_penalty: bool = False
    overlong_tokens: int = 0
    overlong_penalty_factor: float = 0.0
    max_new_tokens: int = 1024  # response-length cap used by the penalty
    mask_no_eos_with_zero: bool = False
    # KL
    kl_ctl: float = 0.0
    kl_estimator: str = "k1"
    # GAE
    discount: float = 1.0
    gae_lambda: float = 1.0
    adv_norm: NormConfig | None = field(default_factory=NormConfig)
    # decoupled PPO / staleness
    recompute_logprob: bool = True
    use_decoupled_loss: bool = True
    behav_imp_weight_cap: float | None = None
    # sampling filters
    dynamic_sampling: bool = False
    # entropy
    entropy_coeff: float = 0.0
    entropy_clamp: float | None = None  # AEnt-style clamped entropy
    log_agg_mode: str = "token-mean"  # token-mean | seq-mean-token-sum | seq-mean-token-mean


@dataclass
class PPOCriticConfig(TrainEngineConfig):
    """Reference cli_args.py:515."""

    value_eps_clip: float = 0.2
    value_loss_type: str = "mse"  # mse | huber
    huber_delta: float = 10.0
    ppo_n_minibatches: int = 4
    mask_no_eos_with_zero: bool = False


@dataclass
class TracingConfig:
    """Distributed rollout tracing (utils/tracing.py): per-request
    trace/span ids minted in the WorkflowExecutor, propagated via the
    ``x-areal-trace`` HTTP header into the inference server and engine,
    with spans/events for queue wait, prefix-cache hits, chunked-prefill
    and decode dispatches, spec-decode accept runs, failover re-dispatch,
    and weight commits landing mid-generation. Disabled by default; when
    off every hot-path call site pays only an ``is not None`` check
    (pinned by a code-inspection test, like the chaos hook)."""

    enabled: bool = False
    # component name stamped on spans (client plane vs each server)
    service: str = "areal"
    # bounded buffer of finished spans (ring; oldest evicted)
    max_spans: int = 4096
    # per-span event cap (drops counted, never unbounded)
    max_events_per_span: int = 256
    # append finished spans as JSON lines here ("" = buffer only; export
    # on demand via Tracer.export_jsonl / the Perfetto converter)
    export_path: str = ""


@dataclass
class MetricsConfig:
    """Unified metrics registry (utils/metrics.py): counters / gauges /
    histograms with labels, scrapeable as Prometheus text on the
    inference server's ``/metrics`` and exported periodically through
    the StatsLogger on the trainer side."""

    enabled: bool = True
    # merge registry scalars into every StatsLogger commit row under
    # this key prefix ("" disables the periodic trainer-side export)
    stats_logger_prefix: str = "metrics/"
    # distinct label-sets per metric before new series coalesce into
    # "__overflow__" (the cardinality guard against raw-rid labels)
    max_label_values: int = 128


@dataclass
class JaxGenConfig:
    """Inference-server engine knobs (replaces SGLangConfig/vLLMConfig,
    reference cli_args.py:533,620 — ours is the in-repo JAX server)."""

    model_path: str = ""
    dtype: str = "bfloat16"
    max_batch_size: int = 64
    prefill_chunk: int = 512  # tokens per prefill chunk (static bucket)
    # > 0 enables intra-prompt chunked prefill (vLLM/SGLang-style): a text
    # prompt longer than this warms its KV in chunks of this size across
    # engine iterations, so one 32k admission cannot stall running decodes
    # for its whole prompt; the slot joins decode only when warm. 0 = off
    # (whole-prompt dispatches, still token-budgeted per loop iteration).
    chunked_prefill_tokens: int = 0
    # preferred name for the chunked-prefill chunk size (serving-plane
    # naming parity with vLLM/SGLang); > 0 overrides
    # ``chunked_prefill_tokens``. Both knobs drive the same machinery.
    prefill_chunk_size: int = 0
    # radix prefix cache (inference/prefix_cache.py): finished sequences
    # register their FULL KV blocks under their token prefix; a later
    # request whose prompt shares that prefix sets cache_len to the covered
    # blocks and prefills only the uncovered suffix. Survives slot churn
    # (unlike enable_prefix_reuse's slot-level clone paths, which remain
    # the zero-dispatch fast path while the source slot is intact).
    # Weight commits version-fence the cache: stale-version blocks are
    # never spliced into a new-version prefill.
    enable_prefix_cache: bool = True
    # token-budget admission control (inference/scheduler.py): total KV
    # tokens committed to running + warming sequences may not exceed this;
    # requests beyond it stay QUEUED instead of thrashing cache eviction,
    # and a request that could never fit is refused outright. 0 = derive
    # from pool capacity (kv_pool_tokens).
    admission_token_budget: int = 0
    # ragged paged-attention Pallas decode kernel
    # (ops/pallas/paged_attention.py): decode attention walks the block
    # table in place — block-table-indexed KV gather inside the kernel,
    # per-query causal masking over ragged cache lengths, fully-masked KV
    # blocks skipped — instead of materializing the gathered [B, NBT*BS]
    # view the XLA path einsums over. TPU backends run the compiled
    # kernel; CPU runs it in interpret mode (parity testing / bench
    # rehearsal). Composes with kv_quant="int8" (scale planes are
    # dequantized inside the kernel — halved KV bytes per decode step).
    # Requires tp_size=1 (a raw pallas_call has no GSPMD partitioning
    # rule); unsupported combinations fall back to the XLA gather path
    # with a one-shot warning + pallas_fallback_total counter. Greedy
    # outputs are token-identical kernel-on vs kernel-off
    # (tests/test_paged_kernel.py pins this).
    use_pallas_decode: bool = False
    # Pallas chunked-prefill flash kernel (ops/pallas/chunked_prefill.py)
    # for the paged Tq>1 dispatches — chunked-prefill warming, radix
    # suffix-prefill, spec-verify windows: query tiles x kv blocks walked
    # straight off the block table with per-query causal masking across
    # the chunk boundary (arbitrary cache_len starts, mid-block radix
    # hits, sliding windows), dead tiles skipped flash-style. Same
    # fallback rules and greedy-identity bar as use_pallas_decode
    # (tests/test_prefill_kernel.py); composes with kv_quant="int8".
    use_pallas_prefill: bool = False
    # "int8" stores the paged KV pool as int8 + per-(row, head) scales:
    # ~half the HBM per cached token, ~double the concurrent sequences at
    # the same kv_pool_tokens byte budget (quality: symmetric per-row
    # quantization; logits drift is small but nonzero). Works under pp
    # serving too (the stage conveyors thread the scale planes).
    kv_quant: str = "none"
    # max queued prompts packed into ONE prefill dispatch (same segment-id
    # stream; block-skipping keeps cost at sum of per-prompt quadratics)
    prefill_batch: int = 4
    max_seq_len: int = 4096
    page_size: int = 128  # KV cache page granularity
    # total tokens the paged KV pool holds (HBM budget for attention state);
    # None = max_batch_size * max_seq_len (capacity parity with a dense
    # per-slot cache). Because slots draw blocks on demand, a pool far
    # smaller than B*S admits the same traffic whenever sequences are
    # shorter than max_seq_len — the paged-attention memory win.
    kv_pool_tokens: int | None = None
    hbm_utilization: float = 0.85
    decode_steps_per_call: int = 8  # multi-step decode inside one jit call
    host: str = "0.0.0.0"
    port: int = 0  # 0 = pick free port
    tp_size: int = 1
    # pipeline-parallel serving: the layer stack (params + paged KV pool)
    # shards over pp_size stages, so models pp x larger than one chip's
    # TP reach can serve (the realhf pipe_runner.py:375-648 pipelined-
    # generation role). Decode latency grows by the stage count; combine
    # with tp_size for pp x tp meshes.
    pp_size: int = 1
    # batch-group rotation for pp decode (every stage busy every tick,
    # ~S x the sequential conveyor's throughput). False forces the
    # sequential conveyor — one batch through all stages per token — for
    # debugging and latency/throughput comparisons
    # (tests/test_pp_decode_latency.py records the trade).
    pp_rotate_decode: bool = True
    random_seed: int = 1
    skip_tokenizer_init: bool = False
    # serving role under prefill/decode disaggregation: "" (generalist,
    # the single-pool default), "prefill" (computes prompt KV, samples the
    # first token, retains the blocks pinned for export to a decode peer),
    # or "decode" (imports shipped KV over POST /import_kv and drives
    # decode; chunked-prefill interleaving is disabled so decode batches
    # stay dense — a fallback full prefill after a refused import still
    # works, it just dispatches whole-prompt). The role rides /ready and
    # /model_info and is registered in name_resolve for role-aware client
    # routing. Overridable via the AREAL_SERVER_ROLE env var set by the
    # fleet provider.
    role: str = ""
    # keep aborted requests' KV in their slots, keyed by rid; the client's
    # abort-resume loop then continues decoding with ZERO re-prefill. The
    # retained attention state may predate a weight update (accepted
    # staleness: per-token versions still record the sampling policy and
    # decoupled PPO recomputes logprobs on the trainer); set False for
    # strict re-prefill-under-new-weights semantics.
    retain_kv_on_abort: bool = True
    # reuse another slot's KV rows when a new request's prompt prefix is
    # already cached there (the GRPO n-samples case: one prefill per prompt
    # GROUP; clones join the batched decode directly via a device-side row
    # copy). Cleared on weight updates so fresh requests always prefill
    # under current weights.
    enable_prefix_reuse: bool = True
    # cross-request PARTIAL prefix sharing (the general radix-tree-reuse
    # case the reference inherits from SGLang): when a new prompt shares at
    # least this many leading tokens with some slot's cached rows, admit it
    # by copying the shared rows and running a suffix-extension dispatch
    # instead of a full prefill. Minimum is a cost cutoff — below it a
    # fresh (batched) prefill is cheaper than copy + lone extend dispatch.
    prefix_extend_min: int = 128
    # Draft-free speculative decoding (vLLM/SGLang "prompt lookup" / n-gram
    # style): "ngram" proposes up to spec_draft_len continuation tokens per
    # slot by matching the sequence's own trailing n-gram against its
    # history, verifies all of them in ONE multi-token paged dispatch, and
    # rolls back rejected tokens by rewinding cache_len (free under the
    # paged pool — no copies). Greedy requests accept by exact argmax
    # match (spec-on output is token-identical to spec-off); sampled
    # requests use rejection sampling so the output distribution is
    # unchanged. Reasoning/math completions are repetitive enough that
    # acceptance rates make decode 1.5-3x faster; batches where fewer
    # than ~a quarter of the slots have an n-gram hit stay on the plain
    # decode_steps_per_call-amortized path (a verify window emits at most
    # one token for a draft-less slot, so a lone repetitive sequence must
    # not drag a diverse batch off multi-step decode). pp_size > 1 falls
    # back to non-speculative with a logged warning. "none" = off.
    spec_decode: str = "none"
    # max draft tokens proposed (and verified) per slot per window; the
    # verify dispatch feeds 1 + spec_draft_len tokens per slot
    spec_draft_len: int = 4
    # adaptive per-sequence draft length: each slot keeps an EWMA of its
    # own acceptance rate and its draft length tracks
    # min + ewma * (max - min) (rounded, clamped), so low-acceptance
    # prompts stop paying dead verify FLOPs while repetitive ones keep the
    # full window. Bounds: [spec_draft_len_min, spec_draft_len_max];
    # spec_draft_len_max = 0 means "= spec_draft_len" (adaptation can
    # shrink but never grow past the static knob — compiled verify shapes
    # are unchanged). spec_draft_len_min = 0 disables adaptation entirely
    # (every slot stays at the static spec_draft_len).
    spec_draft_len_min: int = 1
    spec_draft_len_max: int = 0
    # EWMA smoothing for the per-slot acceptance estimate (higher = adapt
    # faster, noisier)
    spec_adapt_alpha: float = 0.25
    # n-gram match lengths tried longest-first when proposing: the last
    # spec_ngram_max..spec_ngram_min tokens are matched against the
    # sequence's own prompt + output history
    spec_ngram_max: int = 4
    spec_ngram_min: int = 1
    # max seconds the blocking engine-command API (weight updates, staged
    # commits) waits for the engine thread to pick a command up before
    # raising a descriptive TimeoutError naming the pending command (was a
    # hardcoded 600s deep in the engine); covers worst-case compile of a
    # fresh decode/prefill program
    command_timeout_seconds: float = 600.0
    # TTL for retained abort/interrupt KV whose owner never resumes (a
    # client that disconnects mid-interrupt-loop would otherwise pin its
    # slot until LRU pressure): the engine-loop reaper frees entries older
    # than this many seconds and counts them in
    # serving_stats()["retained_kv_reaped_total"]. <= 0 disables the reaper.
    retained_kv_ttl_seconds: float = 300.0
    # priority preemption: when a strictly-higher-priority request cannot
    # be admitted, interrupt the lowest-priority running victim at the next
    # token boundary (KV retained pinned, victim auto-requeued at its
    # original queue position and resumed with zero re-prefill once
    # capacity returns). All-equal-priority traffic — the default — is
    # never preempted, so this is safe to leave on.
    enable_preemption: bool = True
    # server-side default drain budget (POST /drain without an explicit
    # grace, and the launcher's SIGTERM path): in-flight sequences get this
    # many seconds to finish naturally before the engine interrupts the
    # rest at the next token boundary (clients resume token-exactly on a
    # peer). Bounds shutdown wall-time by grace, not max generation length.
    interrupt_grace_seconds: float = 30.0
    # persistent JAX compilation cache directory: relaunch-after-preemption
    # reloads compiled executables from here instead of paying full XLA
    # recompile (utils/jax_cache.configure_compilation_cache). None = off.
    jax_compilation_cache_dir: str | None = None
    # server-side rollout tracing: request spans continue the client's
    # x-areal-trace context with engine-internal events (admission wait,
    # radix hit length, prefill chunks, decode segments, spec accepts,
    # weight commits landing mid-generation). Off = zero request-path cost.
    tracing: TracingConfig = field(default_factory=TracingConfig)


@dataclass
class CircuitBreakerConfig:
    """Per-server circuit breaker for the rollout client plane
    (core/fault_tolerance.py). CLOSED routes normally; enough failures trip
    the server OPEN (zero traffic); a background ``/health`` probe moves a
    cooled-down OPEN server to HALF_OPEN, where bounded trial traffic either
    closes the breaker again or re-opens it."""

    enabled: bool = True
    # consecutive failures that trip CLOSED -> OPEN
    failure_threshold: int = 3
    # sliding window for failure-rate tripping (gray failure: a server that
    # intermittently fails without ever hitting the consecutive threshold)
    window_seconds: float = 60.0
    failure_rate_threshold: float = 0.5
    min_window_requests: int = 8
    # OPEN servers are not even probed until this cooldown elapses
    open_cooldown_seconds: float = 5.0
    # concurrent trial requests allowed in HALF_OPEN
    half_open_max_probes: int = 1
    # background /health probe cadence for OPEN servers
    probe_interval_seconds: float = 5.0
    probe_timeout_seconds: float = 10.0


@dataclass
class DisaggregationConfig:
    """Prefill/decode disaggregated serving (client plane). When enabled,
    ``agenerate`` dispatches the prompt to a prefill-role server (one
    sampled token), asks it to ship the finished KV blocks to a
    decode-role server over ``POST /import_kv`` (versioned, digest-stamped
    chunks on the `utils/wire.py` encode path), and drives decode there —
    the decode server admits the sequence through the retained-KV resume
    path with ZERO re-prefill. Every failure mode is loud and counted,
    never silent: a weight commit landing between prefill and import makes
    the import refuse with 412 and the client falls back to a local full
    prefill on the decode server; the prefill server dying mid-ship takes
    the token-exact re-prefill failover path. Off (the default) leaves the
    single-pool serving plane byte-identical."""

    enabled: bool = False
    # max tokens sampled on the prefill server before handoff (the first
    # token rides back with the prefill response and seeds decode)
    prefill_max_tokens: int = 1
    # KV-ship chunking: target encoded bytes per POST /import_kv chunk
    kv_ship_chunk_mb: int = 8
    # bounded pipelining for the ship stream (chunks in flight ahead of
    # the receiver; same backpressure discipline as weight streaming)
    kv_ship_pipeline_depth: int = 2
    # whole-ship wall budget (export + every chunk + commit); a breach
    # falls back to a local full prefill on the decode server
    kv_ship_timeout_seconds: float = 120.0
    # prompts shorter than this skip disaggregation (shipping KV for a
    # tiny prompt costs more than re-prefilling it locally)
    min_prompt_tokens: int = 0


@dataclass
class FleetConfig:
    """Elastic rollout-fleet controller (areal_tpu/fleet/): closes the loop
    from observed serving load (admission queue depth/wait, TTFT p95,
    in-flight skew, rollout-wait fraction) to fleet size. The controller
    spawns servers through a provider (local subprocess now; slurm/gke share
    the signature), gates newcomers on ``GET /ready`` + a version-checked
    warmup before they enter rotation, and drains scale-in victims AFTER
    removing them from routing so in-flight requests finish or fail over."""

    enabled: bool = False
    # hard fleet-size bounds the policy may never cross
    min_servers: int = 1
    max_servers: int = 4
    # servers the controller bootstraps at start (None = min_servers);
    # ignored when the fleet was already booted by a launcher
    initial_servers: int | None = None
    # how often the background controller thread evaluates the policy
    decide_interval_seconds: float = 5.0
    # policy: "target_tracking" (scale on load signals) | "manual"
    # (set_size() only)
    policy: str = "target_tracking"
    # consecutive breached evaluations required before acting (hysteresis —
    # one spiky sample must not flap the fleet)
    breach_evaluations: int = 2
    # post-action cooldowns: no further scale-out/in until these elapse
    # (scale-in is slower by default; killing warm KV is expensive)
    scale_out_cooldown_seconds: float = 15.0
    scale_in_cooldown_seconds: float = 60.0
    # servers added/removed per decision
    scale_step: int = 1
    # --- target-tracking thresholds (0 disables that signal) ---
    # scale OUT when admission queue depth per server exceeds this ...
    queue_depth_high_per_server: float = 4.0
    # ... and IN when it drops below this on every server
    queue_depth_low_per_server: float = 0.5
    # scale OUT when the fleet-max TTFT p95 exceeds this (seconds)
    ttft_p95_high_seconds: float = 0.0
    # scale OUT when the trainer's rollout-wait fraction (PR 9 StepTimeline
    # counters: blocked-in-wait() wall over elapsed wall) exceeds this
    rollout_wait_fraction_high: float = 0.0
    # scale OUT when the fleet-max inter-token-latency p95 exceeds this
    # (seconds). Primarily a DECODE-pool signal under disaggregation, but
    # honored in single-pool mode too when set.
    itl_p95_high_seconds: float = 0.0
    # --- per-role pools (prefill/decode disaggregation) ---
    # When serving.disaggregation is enabled the controller runs one
    # policy instance per role: the prefill pool scales on admission-queue
    # wait/TTFT and the decode pool on ITL p95/in-flight. Each pool gets
    # its own size bounds below; min_servers/max_servers above then bound
    # the TOTAL. Roles ride the spawn env (AREAL_SERVER_ROLE), /ready,
    # warmup, and the name_resolve role tags the client routes on.
    prefill_min_servers: int = 1
    prefill_max_servers: int = 2
    decode_min_servers: int = 1
    decode_max_servers: int = 4
    # --- lifecycle ---
    # newcomer must pass GET /ready (model loaded AND weights at the
    # required version) within this budget or it is terminated and never
    # enters rotation
    ready_timeout_seconds: float = 300.0
    # SIGTERM -> SIGKILL grace for scale-in victims (the PR 4 drain path:
    # in-flight requests finish or fail over within it)
    drain_grace_seconds: float = 30.0
    # bounded-time drain: before terminating a scale-in victim the
    # controller POSTs /drain with this budget — sequences still running at
    # the deadline are INTERRUPTED at the next token boundary and resume
    # token-exactly on a healthy peer through the failover splice, so drain
    # wall-time is bounded by this grace, not by max generation length.
    # <= 0 skips the interrupt-drain phase (legacy finish-or-fail-over).
    interrupt_grace_seconds: float = 15.0
    # per-server /model_info signal-poll timeout
    signal_timeout_seconds: float = 2.0
    # provider: "local" (subprocess on this host) | "slurm" | "gke" (stubs)
    provider: str = "local"
    # argv template for provider-spawned servers ("{port}"/"{server_id}"
    # substituted); empty = the launcher exports one via
    # AREAL_FLEET_SERVER_ARGV (launcher/local.py)
    server_argv: list[str] = field(default_factory=list)


@dataclass
class ChaosRuleConfig:
    """One deterministic fault-injection rule (utils/chaos.py). ``endpoint``
    is a substring matched against the request path ("*" = all); ``action``
    is one of drop | http_error | timeout | slow | disconnect; ``times`` > 0
    arms the rule for exactly that many matching requests (fail-next-N)."""

    endpoint: str = "*"
    action: str = "http_error"
    probability: float = 1.0
    status: int = 503
    delay_seconds: float = 0.0
    times: int = 0  # 0 = unlimited


@dataclass
class ChaosConfig:
    """Deterministic fault injection for the client request path. Disabled
    by default; when off the request hot path pays only a None check.
    Server-side injection is env-gated instead (``AREAL_CHAOS_SERVER``)."""

    enabled: bool = False
    seed: int = 0
    rules: list[ChaosRuleConfig] = field(default_factory=list)


@dataclass
class RewardServiceConfig:
    """Sandboxed reward-execution plane (areal_tpu/reward_service/): a
    bounded pool of persistent ``python -I`` sandbox workers backing (a)
    the in-process execution fallback every zero-egress TPU pod uses and
    (b) N HTTP service replicas the launcher can spawn alongside the
    inference servers. The client fronts the replicas with circuit
    breakers + least-inflight routing and falls back to the local pool,
    so arbitrary code-execution rewards can never wedge the rollout
    plane."""

    # spawn/use the HTTP service (off = local bounded pool only)
    enabled: bool = False
    # service replicas launcher/local.py spawns alongside the servers
    replicas: int = 1
    # explicit service addresses (skip name_resolve discovery)
    addresses: list[str] = field(default_factory=list)
    # bind address; must stay reachable at the gethostip() the replica
    # REGISTERS in name_resolve (0.0.0.0 like the generation server — a
    # loopback bind would register an address nobody can connect to)
    host: str = "0.0.0.0"
    port: int = 0  # 0 = pick a free port per replica
    # sandbox workers per service replica AND in the local fallback pool
    num_workers: int = 4
    # tasks a worker executes before it is retired and respawned
    recycle_after: int = 64
    # admission bound: tasks in flight or queued; beyond it the service
    # answers 429 + Retry-After and the pool raises PoolSaturated
    max_pending: int = 256
    # per-task wall deadline; breach = process-group kill + respawn
    task_timeout: float = 10.0
    memory_mb: int = 512
    cpu_seconds: int = 0  # 0 = derived from task_timeout
    # client-side HTTP knobs (arequest_with_retry)
    request_timeout: float = 60.0
    request_retries: int = 3
    # whole-call deadline incl. retries/backoff; 0 disables
    total_timeout: float = 120.0
    # fall back to the in-process pool when no replica is reachable
    fallback_local: bool = True
    # re-resolve service replicas from name_resolve this often (seconds)
    discovery_interval: float = 30.0
    # SIGTERM: seconds in-flight tasks get before the pool group-kills
    drain_grace_seconds: float = 10.0
    # route agentic tool-env sandbox calls (examples/tir) through the
    # same plane (service when reachable, bounded pool otherwise)
    tool_execution: bool = True
    # per-tool latency/failure metrics + tool-call spans + turn-level
    # staleness accounting in the workflow tool loop
    tool_metrics: bool = True
    breaker: CircuitBreakerConfig = field(default_factory=CircuitBreakerConfig)
    chaos: ChaosConfig | None = None
    tracing: TracingConfig = field(default_factory=TracingConfig)


@dataclass
class InferenceEngineConfig:
    """Client/rollout control (reference cli_args.py:786)."""

    experiment_name: str = ""
    trial_name: str = ""
    max_concurrent_rollouts: int | None = None
    queue_size: int | None = None
    consumer_batch_size: int = 1
    max_head_offpolicyness: int = 0
    enable_rollout_tracing: bool = False
    check_trajectory_format: bool = False
    schedule_policy: str = "round_robin"
    setup_timeout: float = 120.0
    request_timeout: float = 3600.0
    request_retries: int = 3
    pause_grace_period: float = 0.0
    # pause/continue fan-out request timeout (was a hardcoded 60.0)
    pause_continue_request_timeout: float = 60.0
    # re-query name_resolve for late-registered servers at most this often;
    # 0 disables (env/explicit address lists never refresh)
    server_refresh_interval: float = 30.0
    # --- fault tolerance (core/fault_tolerance.py) ---
    breaker: CircuitBreakerConfig = field(default_factory=CircuitBreakerConfig)
    # per-request re-dispatches to a different server after a failed
    # generate attempt (accumulated tokens replay as the new prompt)
    failover_retries: int = 3
    # client-side backoff between abort-resume attempts when the server
    # made NO forward progress (paused engine / drained queue); interrupt
    # responses that did emit tokens resume immediately (was a hardcoded
    # 0.2 in the resume loop)
    abort_resume_backoff_seconds: float = 0.2
    # overall wall-clock budget for one agenerate call including all
    # failover re-dispatches; 0 = no overall deadline
    failover_deadline_seconds: float = 0.0
    # update_weights tolerates per-server failure (the failed server is
    # quarantined) as long as at least this fraction of servers took the
    # update; below it the step raises
    update_weights_min_healthy_fraction: float = 0.5
    # cache-aware routing: route requests by a hash of their leading prompt
    # tokens (rendezvous/highest-random-weight over the ROUTABLE servers),
    # so a GRPO group's group_size identical prompts — and a multi-turn
    # conversation's growing prefix — land on the server that already holds
    # their KV prefix in its radix cache. Layered UNDER the breaker plane:
    # rid affinity (a resumed request's server holds its exact KV) still
    # wins, and a tripped breaker overrides affinity entirely.
    cache_aware_routing: bool = True
    # how many leading prompt tokens feed the affinity hash; conversations
    # that share at least this prefix co-locate. 0 disables the signal
    # (equivalent to cache_aware_routing=False).
    route_affinity_prefix_tokens: int = 512
    # hotspot guard: when the affinity-preferred server already carries
    # this many MORE in-flight requests (from this client) than the
    # least-loaded routable candidate, the request falls back to the
    # configured load policy instead — a fleet whose prompts all share one
    # long template prefix must not collapse onto a single server. Sized
    # so a GRPO group (typically <= 16 clones) still co-locates. 0
    # disables the guard (affinity always wins).
    route_affinity_max_inflight_skew: int = 32
    # pipelined weight sync: how many encoded/staged chunks the producer may
    # run AHEAD of the slowest server's stream (chunk i+1 gathers/encodes
    # while chunk i is in flight). Bounds staging RAM at roughly
    # depth x chunked_mem_mb beyond the in-flight chunk; 1 = classic
    # lockstep (encode only after every server took the previous chunk)
    weight_update_pipeline_depth: int = 2
    # --- peer-to-peer weight propagation (utils/propagation.py) ---
    # relay the http chunk stream through the fleet instead of pushing a
    # full copy per server: the trainer streams to weight_propagation_fanout
    # ROOT servers and each server forwards staged chunks to at most that
    # many children over POST /relay_weights (staging semantics — version
    # tags, 412 delta guard, torn-stream supersede — apply per hop).
    # Trainer egress per commit drops from N x to fanout x model bytes and
    # commit latency goes O(log N). A parent that fails mid-stream falls
    # back to direct trainer push for its subtree; OPEN-breaker servers
    # never enter the tree (quarantine semantics unchanged). Off = the
    # PR 5 per-server direct streams.
    weight_propagation_enabled: bool = False
    # trainer-side root count AND per-server relay fan-out (>= 1; 1 = a
    # chain — minimal egress, maximal depth)
    weight_propagation_fanout: int = 2
    # shared secret for /relay_weights and /push_weights_to_peer (sent as
    # x-areal-relay-token; servers check it against AREAL_RELAY_TOKEN).
    # Empty = authentication off (single-tenant dev runs).
    weight_propagation_token: str = ""
    # warmup_server (fleet scale-out, stale-newcomer admission) first asks
    # a healthy in-rotation peer to push its current weights to the
    # newcomer (POST /push_weights_to_peer) and only falls back to the
    # trainer's disk artifact — scale-out stops billing the trainer
    peer_warmup: bool = True
    # per-server rollout concurrency: when set, the staleness manager's
    # max-concurrent-rollout capacity is rollouts_per_server x the LIVE
    # fleet size, recomputed on every membership change (scale-out raises
    # the ceiling, scale-in lowers it) instead of being frozen at the
    # boot-time server count. None keeps the static max_concurrent_rollouts
    rollouts_per_server: int | None = None
    # prefill/decode disaggregated serving (KV shipping + role routing)
    disaggregation: DisaggregationConfig = field(
        default_factory=DisaggregationConfig
    )
    # elastic rollout-fleet controller (areal_tpu/fleet/)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    # client-side deterministic fault injection (tests/rehearsals)
    chaos: ChaosConfig | None = None
    # distributed rollout tracing (client plane: rollout + generate spans,
    # header propagation to the servers); off = zero hot-path cost
    tracing: TracingConfig = field(default_factory=TracingConfig)


@dataclass
class _TimerConfig:
    freq_epochs: int | None = None
    freq_steps: int | None = None
    freq_secs: int | None = None


@dataclass
class SaverConfig(_TimerConfig):
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = "/tmp/areal_tpu/experiments"
    # --- retention GC (long runs must not fill the disk) ---
    # keep only the newest N checkpoints (None = keep everything)
    keep_last: int | None = None
    # additionally keep every checkpoint whose global_step % keep_every == 0
    # (sparse long-horizon history under a tight keep_last)
    keep_every: int | None = None


@dataclass
class EvaluatorConfig(_TimerConfig):
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = "/tmp/areal_tpu/experiments"


@dataclass
class RecoverConfig:
    mode: str = "disabled"  # disabled | auto | fault | resume
    freq_epochs: int | None = None
    freq_steps: int | None = None
    freq_secs: int | None = None
    retries: int = 3
    # --- preemption semantics (utils/recover.py PreemptionGuard) ---
    # SIGTERM/preemption-notice -> pause + drain + checkpoint must finish
    # within this budget (preemptible TPU slices give ~30s notice)
    grace_period_seconds: float = 30.0
    # of the grace budget, at most this long is spent draining in-flight
    # rollouts (the rest is reserved for the checkpoint write itself)
    drain_timeout_seconds: float = 20.0
    # --- launcher relaunch backoff (launcher/local.py) ---
    # capped exponential delay between relaunches of a crashing trial, so a
    # deterministic startup failure doesn't hot-loop the trial
    relaunch_backoff_seconds: float = 1.0
    relaunch_backoff_max_seconds: float = 60.0
    # --- topology-independent checkpoints (utils/checkpoint.py) ---
    # engine format for recover dumps: "sharded" writes the re-shardable
    # digest-manifest format (an N-host checkpoint resumes on any mesh
    # shape, corruption refused by digest); "orbax" keeps the PR 4
    # same-topology format
    checkpoint_format: str = "sharded"
    # verify per-shard digests BEFORE any weight loads on resume; a failing
    # dump falls back to the newest retained dump that verifies
    verify_digests: bool = True
    # retain the newest N committed dump directories (>= 1). N >= 2 gives
    # the corruption fallback a previous checkpoint to land on; the price
    # is N engine checkpoints on disk (plus one transiently during a dump)
    keep_dumps: int = 2


@dataclass
class WatchdogConfig:
    """Hung-trainer detector (utils/watchdog.py): a daemon thread that
    requires the training loop to ``beat()`` at least every
    ``timeout_seconds``; on a miss it dumps every thread's stack and exits
    nonzero, so the launcher relaunches a trainer that is WEDGED (deadlock,
    lost collective, hung rollout wait) rather than dead."""

    enabled: bool = False
    # worst-case legitimate gap between beats: compile + slowest train step
    # or rollout wait; crossing it means wedged, not slow
    timeout_seconds: float = 1800.0
    poll_interval_seconds: float = 10.0
    # distinct from PREEMPTION_EXIT_CODE(42) so logs tell hangs from drains
    exit_code: int = 43


@dataclass
class WandBConfig:
    mode: str = "disabled"
    project: str | None = None
    entity: str | None = None
    name: str | None = None
    # passthrough wandb.init knobs (reference cli_args WandBConfig parity)
    wandb_base_url: str | None = None
    wandb_api_key: str | None = None
    job_type: str | None = None
    group: str | None = None
    notes: str | None = None
    tags: list | None = None
    config: dict | None = None
    id_suffix: str | None = None


@dataclass
class TensorBoardConfig:
    path: str | None = None


@dataclass
class StatsLoggerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    fileroot: str = "/tmp/areal_tpu/experiments"
    wandb: WandBConfig = field(default_factory=WandBConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    # trainer-side periodic export of the unified metrics registry
    # (utils/metrics.py): registry scalars are merged into every commit row
    metrics: MetricsConfig = field(default_factory=MetricsConfig)


@dataclass
class ClusterSpecConfig:
    name_resolve: NameResolveConfig = field(default_factory=NameResolveConfig)
    cluster_name: str = "local"
    fileroot: str = "/tmp/areal_tpu/experiments"
    n_chips_per_host: int = 4
    n_nodes: int = 1


@dataclass
class DatasetConfig:
    path: str = ""  # HF dataset name or local path
    type: str = "rl"  # rl | sft | rw
    batch_size: int = 8
    shuffle: bool = True
    pin_memory: bool = False
    num_workers: int = 0
    drop_last: bool = True
    max_length: int | None = None


@dataclass
class ProfilerConfig:
    """Windowed jax.profiler capture (utils/profiling.py; the reference's
    torch-profiler attribution role, realhf/base/monitor.py:404-610)."""

    enabled: bool = False
    dir: str = "/tmp/areal_tpu/profiles"
    start_step: int = 2  # skip compile steps
    num_steps: int = 2


@dataclass
class StepTimelineConfig:
    """Training-plane step-time attribution (utils/step_timeline.py):
    per-step phase breakdown (rollout wait / logp recompute / advantage /
    train / weight sync / checkpoint) with a phases-sum-to-wall-clock
    assertion, goodput (compute fraction) and per-step MFU/TFLOPs-per-chip
    from the analytic FLOPs math, jax memory + recompile telemetry, a
    ``trainer`` flight-recorder channel, and one ``train.step`` tracing
    span per step stamped with the weight version the step produces (the
    cross-plane Perfetto join key). Runs once per STEP — never per token;
    with tracing off the span plumbing costs only ``is not None``."""

    enabled: bool = True
    # warn (once) + count when |wall - sum(phases)| / wall exceeds this
    tolerance: float = 0.05
    # steps before the recompile detector freezes: traces during warmup
    # (first compiles, shape-bucket discovery) are expected; any tracing
    # of a jitted function AFTER the freeze is flagged as a recompile
    warmup_steps: int = 2
    # sample jax device memory_stats + live-array bytes every step
    # (gauges absent — not zero — on backends without memory_stats)
    memory_telemetry: bool = True
    # count tracings per jitted function; one-shot warning + counter
    # metric on a re-trace after warmup (the silent shape-bucket-miss)
    recompile_detector: bool = True
    # ring size of the flight recorder's ``trainer`` channel (last N
    # step breakdowns, dumped on watchdog/InjectedCrash/SIGTERM)
    trainer_channel_steps: int = 64


@dataclass
class RLHealthConfig:
    """RL training-health observatory (utils/rl_health.py): per-step
    distribution telemetry for the ALGORITHM plane — staleness mix from
    per-token ``versions``, importance/behavior ratios + clip and cap
    trigger fractions, reward raw-vs-clipped distributions, entropy/KL
    estimates, advantage stats, generation length/truncation, and a cheap
    degenerate-output detector — exported as ``areal_rl_*`` registry
    instruments, ``rl_health/*`` StatsLogger scalars, and events on the
    ``train.step`` tracing span; plus an anomaly sentinel: a declarative
    rule table (non-finite loss/grad, entropy floor, ratio blow-up,
    staleness spike, reward collapse/flatline, repetition spike) evaluated
    once per step with hysteresis. A firing rule latches
    ``areal_rl_anomaly_total{rule}``, writes a flight-recorder ``anomaly``
    entry with the full offending-batch stats, dumps the recorder
    atomically, and drives the configured guardrail action. Runs once per
    STEP on host-side numpy already in the update path; disabled, the hot
    paths pay only ``is not None`` checks (code-inspection pinned)."""

    enabled: bool = True
    # default guardrail when a rule fires: "warn" (log + telemetry only),
    # "pause_rollout" (WorkflowExecutor.pause — stop feeding new episodes
    # while the operator looks), or "halt" (raise RLHealthHalt BEFORE the
    # step's checkpoint commits, so a poisoned step never becomes the
    # resume point)
    action: str = "warn"
    # per-rule action overrides, e.g. {"non_finite_loss": "halt"}
    rule_actions: dict[str, str] = field(default_factory=dict)
    # consecutive breached evaluations before a rule fires (hysteresis; a
    # one-step blip never trips a guardrail). non_finite_loss always fires
    # on the first breach — one NaN step is already one too many
    consecutive: int = 2
    # entropy floor (nats): the per-token Monte-Carlo entropy estimate
    # (mean -logprob of sampled tokens) falling below this means the
    # policy has collapsed toward deterministic outputs
    entropy_floor: float = 0.01
    # importance-ratio p99 cap: exp(prox_logp - behav_logp) tail beyond
    # this means the data is too off-policy to trust the update
    ratio_p99_cap: float = 4.0
    # per-token staleness (current weight version - token version) p95
    # threshold; meaningful values sit near max_head_offpolicyness
    staleness_p95_max: float = 8.0
    # trailing window (steps, incl. current) for reward collapse/flatline
    reward_window_steps: int = 8
    # flatline: std of per-step mean rewards over the window below this
    # (with a FULL window) — the reward signal died
    reward_std_floor: float = 1e-6
    # collapse: current mean reward below trailing-window mean by more
    # than this absolute drop; <= 0 disables the drop check
    reward_collapse_drop: float = 0.5
    # repetition spike: mean max-n-gram-loop fraction of generated tokens
    # above this (degenerate looping output)
    repetition_max_frac: float = 0.5
    # ring of recent per-step snapshots kept on the flight recorder's
    # ``rl_health`` channel (the context dumped next to an anomaly)
    ring_steps: int = 64
    # publish a compact status JSON (last step stats + last anomaly) to
    # name_resolve for the `areal-tpu-top` operator CLI
    publish_status: bool = True
    # filled from BaseExperimentConfig (status key namespacing)
    experiment_name: str = ""
    trial_name: str = ""


@dataclass
class LauncherConfig:
    inference_server_cpus_per_chip: int = 4
    inference_server_mem_per_chip: int = 32768
    trainer_cpus_per_chip: int = 4
    trainer_mem_per_chip: int = 32768
    inference_server_env_vars: dict[str, str] = field(default_factory=dict)
    trainer_env_vars: dict[str, str] = field(default_factory=dict)
    # multi-host training (the torchrun replacement): spawn this many trainer
    # processes wired together via jax.distributed (parallel/distributed.py);
    # each process drives its local chips and the GSPMD mesh spans all of
    # them. 0 = derive: the slurm/GKE launchers compute the host count from
    # the allocation mode (controller/scheduling.plan_worker_sets); the
    # LOCAL launcher uses 1 (a single process drives every local chip
    # under GSPMD — multi-process locally is only for multi-host testing)
    trainer_processes: int = 0


@dataclass
class BaseExperimentConfig:
    """Reference cli_args.py:1145."""

    experiment_name: str = "experiment"
    trial_name: str = "trial"
    cluster: ClusterSpecConfig = field(default_factory=ClusterSpecConfig)
    allocation_mode: str = "d1"
    seed: int = 1
    total_train_epochs: int = 1
    total_train_steps: int | None = None
    total_train_n_seqs: int | None = None
    tokenizer_path: str = ""
    train_dataset: DatasetConfig = field(default_factory=DatasetConfig)
    valid_dataset: DatasetConfig | None = None
    saver: SaverConfig = field(default_factory=SaverConfig)
    checkpointer: SaverConfig = field(default_factory=SaverConfig)
    evaluator: EvaluatorConfig = field(default_factory=EvaluatorConfig)
    recover: RecoverConfig = field(default_factory=RecoverConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    stats_logger: StatsLoggerConfig = field(default_factory=StatsLoggerConfig)
    launcher: LauncherConfig = field(default_factory=LauncherConfig)
    profiler: ProfilerConfig = field(default_factory=ProfilerConfig)
    step_timeline: StepTimelineConfig = field(
        default_factory=StepTimelineConfig
    )
    rl_health: RLHealthConfig = field(default_factory=RLHealthConfig)

    def __post_init__(self):
        # propagate experiment/trial names into sub-configs left at defaults
        for sub in (
            "saver",
            "checkpointer",
            "evaluator",
            "stats_logger",
            "rl_health",
        ):
            c = getattr(self, sub, None)
            if c is not None and not c.experiment_name:
                c.experiment_name = self.experiment_name
            if c is not None and not c.trial_name:
                c.trial_name = self.trial_name


@dataclass
class SFTConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class RWConfig(BaseExperimentConfig):
    model: TrainEngineConfig = field(default_factory=TrainEngineConfig)


@dataclass
class GRPOConfig(BaseExperimentConfig):
    async_training: bool = True
    # trainer -> inference weight transfer: "disk" (safetensors + mmap load),
    # "http" (no-disk streamed tensors, io_struct.WeightUpdateMeta.from_http)
    weight_update: str = "disk"
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    rollout: InferenceEngineConfig = field(default_factory=InferenceEngineConfig)
    server: JaxGenConfig = field(default_factory=JaxGenConfig)
    actor: PPOActorConfig = field(default_factory=PPOActorConfig)
    ref: TrainEngineConfig | None = None
    # sandboxed reward-execution plane (service replicas + bounded pool)
    reward_service: RewardServiceConfig = field(
        default_factory=RewardServiceConfig
    )


@dataclass
class PPOConfig(GRPOConfig):
    critic: PPOCriticConfig = field(default_factory=PPOCriticConfig)


def get_save_path(cfg) -> str:
    return os.path.join(
        cfg.fileroot, cfg.experiment_name, cfg.trial_name
    )
