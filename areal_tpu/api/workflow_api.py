"""RolloutWorkflow ABC (reference: areal/api/workflow_api.py:11)."""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from areal_tpu.api.engine_api import InferenceEngine


class RolloutWorkflow(abc.ABC):
    @abc.abstractmethod
    async def arun_episode(
        self, engine: "InferenceEngine", data: dict[str, Any]
    ) -> dict[str, Any] | None:
        """Run one episode (possibly many model calls); return a padded
        tensor-dict trajectory batch, or None to drop the episode."""
        ...
