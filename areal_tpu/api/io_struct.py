"""IO structs shared between rollout, inference, and training.

Capability parity with the reference's ``areal/api/io_struct.py``:
``ModelRequest``/``ModelResponse`` (with **per-token output_versions** — the
load-bearing piece of staleness-aware decoupled PPO), ``FinetuneSpec``,
``WeightUpdateMeta``, ``SaveLoadMeta``, ``RolloutStat``, ``StepInfo``.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from dataclasses import dataclass, field

from areal_tpu.api.cli_args import GenerationHyperparameters


@dataclass
class ModelRequest:
    """One generation request (reference io_struct.py:21)."""

    rid: str = field(default_factory=lambda: str(uuid.uuid4()))
    input_ids: list[int] = field(default_factory=list)
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    text: str | None = None
    metadata: dict = field(default_factory=dict)
    tokenizer: object | None = None
    image_data: list | None = None


@dataclass
class ModelResponse:
    """Generation result (reference io_struct.py:48). ``output_versions[i]`` is
    the weight version that produced output token i — interrupted requests
    resumed after a weight update carry mixed versions."""

    input_tokens: list[int] = field(default_factory=list)
    output_tokens: list[int] = field(default_factory=list)
    output_logprobs: list[float] = field(default_factory=list)
    output_versions: list[int] = field(default_factory=list)
    stop_reason: str = "length"  # "stop" | "length" | "abort"
    latency: float = 0.0
    ttft: float = 0.0  # time to first token
    itl: list[float] = field(default_factory=list)  # inter-token latencies
    tokenizer: object | None = None

    @property
    def input_len(self) -> int:
        return len(self.input_tokens)

    @property
    def output_len(self) -> int:
        return len(self.output_tokens)


@dataclass
class FinetuneSpec:
    """Dataset-size-derived schedule spec (reference io_struct.py:77)."""

    total_train_epochs: int
    dataset_size: int
    train_batch_size: int

    @property
    def steps_per_epoch(self) -> int:
        return (self.dataset_size + self.train_batch_size - 1) // self.train_batch_size

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch

    def is_epoch_last_step(self, step: int) -> bool:
        return (step + 1) % self.steps_per_epoch == 0


@dataclass
class ParamSpec:
    """Per-parameter metadata for weight transfer (reference io_struct.py:93)."""

    name: str
    shape: tuple
    dtype: str


# Request-body ceiling of the generation server (aiohttp client_max_size).
# ONE home for the number: the server sizes its app with it and the HTTP
# weight-push path validates each serialized chunk against it CLIENT-side,
# so a WeightUpdateMeta.chunked_mem_mb too large for the server fails with
# a clear error naming the knob instead of an opaque 413.
SERVER_CLIENT_MAX_SIZE = 2 * 1024**3


@dataclass
class WeightUpdateMeta:
    """How trainer weights reach inference servers (reference io_struct.py:105).

    type="disk": trainer writes safetensors to ``path``; servers mmap+load.
    type="device": trainer transfers live jax arrays (colocated engines or
    cross-slice transfer); ``chunked_mem_mb`` bounds staging-buffer size.
    type="http": trainer streams safetensors-serialized chunks straight to
    each server's /update_weights_from_tensor endpoint — the disaggregated
    no-disk path (reference NCCL broadcast, fsdp_engine.py:359-401, without
    the cross-job process group); ``chunked_mem_mb`` bounds chunk size and
    is validated client-side against ``SERVER_CLIENT_MAX_SIZE`` at push
    time (an oversized chunk fails with an error naming this knob, not an
    opaque 413).
    type="shm": same-host disaggregated fast path — trainer writes chunks
    into /dev/shm (RAM-backed tmpfs, no TCP payload, no disk) and servers
    mmap them straight into device_put; only a tiny JSON notification rides
    HTTP. The closest analogue of the reference's NCCL same-node broadcast
    for separate-process engines sharing a host.
    type="device_transfer": cross-PROCESS device path — servers pull the
    trainer's staged buffers through JAX's transfer service straight into
    their own device memory (utils/device_transfer): no safetensors body,
    no host-RAM staging; the data plane is the platform's DMA/socket
    transport. The closest analogue of the reference's dedicated NCCL
    broadcast group (fsdp_engine.py:359-401) for disaggregated deployments,
    including cross-host.
    type="lora": adapter-only push — just the rank-r LoRA factors go to
    /update_lora_weights (or the colocated equivalent) and the serving side
    merges against its retained base; a sync ships megabytes, not the full
    parameter set (reference SGLang adapter hot-swap,
    areal/engine/sglang_remote.py:82-106).
    """

    # "disk" | "device" | "http" | "shm" | "device_transfer" | "lora"
    type: str = "disk"
    path: str | None = None
    chunked_mem_mb: int = 1024
    # wire dtype for the streamed paths (http/shm/device_transfer): cast
    # each leaf to this dtype on device BEFORE shipping (e.g. "bfloat16"
    # halves the wire bytes of an fp32-trained model; the server casts back
    # to its serving dtype on apply). None = ship the training dtype.
    wire_dtype: str | None = None
    # delta-aware leaf skipping (http/shm): per-leaf content fingerprints
    # (blake2b over the materialized host bytes) let consecutive pushes ship
    # ONLY leaves that changed since the last successful push — frozen-base
    # LoRA-adjacent runs ship megabytes instead of the full tree. The first
    # push (and any push after the client's server set changes) ships
    # everything. Not supported on device_transfer (no host bytes to
    # fingerprint exactly).
    delta_only: bool = False

    @classmethod
    def from_disk(
        cls, experiment_name: str, trial_name: str, fileroot: str, name: str = "default"
    ) -> "WeightUpdateMeta":
        path = f"{fileroot}/{experiment_name}/{trial_name}/weight_update/{name}"
        return cls(type="disk", path=path)

    @classmethod
    def from_device(cls, chunked_mem_mb: int = 1024) -> "WeightUpdateMeta":
        return cls(type="device", chunked_mem_mb=chunked_mem_mb)

    @classmethod
    def from_shm(
        cls,
        chunked_mem_mb: int = 1024,
        wire_dtype: str | None = None,
        delta_only: bool = False,
    ) -> "WeightUpdateMeta":
        return cls(
            type="shm",
            chunked_mem_mb=chunked_mem_mb,
            wire_dtype=wire_dtype,
            delta_only=delta_only,
        )

    @classmethod
    def from_http(
        cls,
        chunked_mem_mb: int = 512,
        wire_dtype: str | None = None,
        delta_only: bool = False,
    ) -> "WeightUpdateMeta":
        return cls(
            type="http",
            chunked_mem_mb=chunked_mem_mb,
            wire_dtype=wire_dtype,
            delta_only=delta_only,
        )

    @classmethod
    def from_device_transfer(
        cls, chunked_mem_mb: int = 512, wire_dtype: str | None = None
    ) -> "WeightUpdateMeta":
        return cls(
            type="device_transfer",
            chunked_mem_mb=chunked_mem_mb,
            wire_dtype=wire_dtype,
        )

    @classmethod
    def from_lora(cls) -> "WeightUpdateMeta":
        return cls(type="lora")


@dataclass
class SaveLoadMeta:
    """Checkpoint save/load request (reference io_struct.py:197)."""

    path: str
    weight_format: str = "hf"  # "hf" (safetensors) | "orbax" | "sharded" (manifest)
    with_optim: bool = False
    tokenizer: object | None = None
    base_model_path: str | None = None


@dataclass
class RolloutStat:
    """Counters for the rollout runtime (reference io_struct.py:208)."""

    submitted: int = 0
    accepted: int = 0
    running: int = 0
    rejected: int = 0


@dataclass
class StepInfo:
    """Training progress marker (reference io_struct.py:215)."""

    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0
    steps_per_epoch: int = 0

    def next(self) -> "StepInfo":
        ep_last = (self.epoch_step + 1) >= self.steps_per_epoch
        return StepInfo(
            epoch=self.epoch + 1 if ep_last else self.epoch,
            epoch_step=0 if ep_last else self.epoch_step + 1,
            global_step=self.global_step + 1,
            steps_per_epoch=self.steps_per_epoch,
        )


@dataclass
class TimedResult:
    """A rollout trajectory stamped with its creation time."""

    t: float
    data: dict

    @classmethod
    def now(cls, data: dict) -> "TimedResult":
        return cls(t=time.monotonic_ns(), data=data)
