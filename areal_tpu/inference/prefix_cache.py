"""Radix prefix cache: token sequences -> full KV blocks in the paged pool.

The SGLang signature feature (AReaL-lite's default backend, PAPER.md §1) that
makes GRPO-style rollouts cheap: the same prompt is sent ``group_size`` times
and multi-turn/agentic workloads re-send a growing conversation prefix every
turn. This cache maps token prefixes to KV blocks that some earlier request
already computed, so a hit sets the new sequence's ``cache_len`` to the
covered prefix and prefill runs only on the uncovered suffix.

Design, deliberately narrow:

- **Exact match on FULL blocks only.** Because blocks are fixed-size, the
  radix tree degenerates to a trie whose edges are ``block_size``-token
  chunks; children are keyed by the full chunk tuple, so lookup is one dict
  probe per block. Partially-filled tail blocks are never cached — the slot
  paths (clone/extension in the engine) handle sub-block sharing with the
  existing copy-on-write ``writable`` discipline.
- **One pool reference per node.** Inserting a chunk increfs its block once
  on behalf of the cache; evicting the node decrefs it. Sequences that match
  take their OWN references, so an eviction under a running sequence can
  never free rows it is attending (the pool refcount protects the memory;
  the pin protects the node).
- **Refcount-pinned active nodes.** ``pin``/``unpin`` guard the matched path
  of every admitted sequence; LRU eviction (oldest ``last_use`` first) only
  ever removes unpinned leaves, walking toward the root as children vanish.
- **Version fencing.** Every node is tagged with the weight version its rows
  were computed under. ``match`` only traverses nodes tagged with the
  cache's current version, and ``on_weights_changed`` (called on every
  weight commit) bumps the version and immediately evicts every unpinned
  stale node — stale-version blocks are therefore never spliced into a
  new-version prefill, and pinned stale nodes (held by in-flight sequences)
  are reaped the moment their last pin drops.

Pure host bookkeeping; the engine loop is the single owner (not
thread-safe), same discipline as :class:`BlockPool`.
"""

from __future__ import annotations

import dataclasses

from areal_tpu.inference.block_pool import BlockPool


@dataclasses.dataclass
class RadixNode:
    """One full KV block's worth of cached tokens."""

    key: tuple  # the block_size tokens this node's block holds
    block_id: int
    version: int  # weight version the rows were computed under
    parent: "RadixNode | None"
    children: dict = dataclasses.field(default_factory=dict)
    pins: int = 0
    last_use: float = 0.0

    @property
    def depth_tokens(self) -> int:
        n, d = self, 0
        while n.parent is not None:
            d += len(n.key)
            n = n.parent
        return d


@dataclasses.dataclass
class PrefixMatch:
    """Result of :meth:`RadixPrefixCache.match`: ``blocks[i]`` holds tokens
    ``tokens[i*block_size : (i+1)*block_size]``; ``covered`` is the total
    token count (always a multiple of ``block_size``)."""

    covered: int
    blocks: list
    nodes: list

    def __bool__(self) -> bool:
        return self.covered > 0


class RadixPrefixCache:
    """Trie of full KV blocks over the shared :class:`BlockPool`."""

    def __init__(self, pool: BlockPool, clock=None):
        self.pool = pool
        self.block_size = pool.block_size
        self.version = 0
        self._root = RadixNode(key=(), block_id=-1, version=-1, parent=None)
        self._n_nodes = 0
        self._tick = 0  # monotonic logical clock for LRU (injectable-free)
        self._clock = clock
        # observability (engine /model_info + StatsLogger surface)
        self.hit_tokens_total = 0
        self.miss_tokens_total = 0
        self.evicted_blocks_total = 0
        self.inserted_blocks_total = 0

    # ------------------------------------------------------------------

    @property
    def n_cached_blocks(self) -> int:
        return self._n_nodes

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock()
        self._tick += 1
        return float(self._tick)

    def _chunks(self, tokens) -> list[tuple]:
        bs = self.block_size
        n_full = len(tokens) // bs
        return [tuple(tokens[i * bs : (i + 1) * bs]) for i in range(n_full)]

    # ------------------------------------------------------------------
    # lookup / insert
    # ------------------------------------------------------------------

    def match(self, tokens) -> PrefixMatch:
        """Longest cached prefix of ``tokens`` in whole blocks, current
        weight version only. Does NOT take references or pins — the caller
        increfs the returned blocks into its own table and pins the nodes
        for the sequence's lifetime (``pin``), mirroring how slots own
        their block-table references."""
        node = self._root
        blocks: list[int] = []
        nodes: list[RadixNode] = []
        now = self._now()
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None or child.version != self.version:
                break
            child.last_use = now
            blocks.append(child.block_id)
            nodes.append(child)
            node = child
        covered = len(blocks) * self.block_size
        # NOTE: hit/miss token counters are charged by the ENGINE on the
        # admission decision (a match that later fails block allocation is
        # not a hit), not here.
        return PrefixMatch(covered=covered, blocks=blocks, nodes=nodes)

    def insert(self, tokens, block_ids) -> int:
        """Register ``tokens``' full blocks (``block_ids[i]`` holds chunk
        ``i``) under the CURRENT version. Existing current-version nodes are
        kept (first writer wins — both physical blocks hold identical rows,
        and the inserter's copy stays owned by its slot); stale-version
        nodes on the path are refreshed in place to the new block. Returns
        the number of new pool references the cache took."""
        node = self._root
        took = 0
        now = self._now()
        chunks = self._chunks(tokens)
        for i, chunk in enumerate(chunks):
            blk = int(block_ids[i])
            child = node.children.get(chunk)
            if child is not None:
                if child.version != self.version:
                    # refresh: same tokens, new-weights rows. Swap the
                    # cache's reference to the new block; pinned holders
                    # keep their own refs on the OLD block untouched.
                    self.pool.decref([child.block_id])
                    self.pool.incref([blk])
                    child.block_id = blk
                    child.version = self.version
                child.last_use = now
                node = child
                continue
            self.pool.incref([blk])
            child = RadixNode(
                key=chunk, block_id=blk, version=self.version, parent=node,
                last_use=now,
            )
            node.children[chunk] = child
            node = child
            self._n_nodes += 1
            self.inserted_blocks_total += 1
            took += 1
        return took

    # ------------------------------------------------------------------
    # pinning / eviction / fencing
    # ------------------------------------------------------------------

    def pin(self, nodes) -> None:
        for n in nodes:
            n.pins += 1

    def unpin(self, nodes) -> None:
        """Release pins; stale nodes whose last pin just dropped are reaped
        immediately (leaf-first) so fenced-off KV stops occupying the pool
        as soon as its last in-flight user finishes."""
        for n in nodes:
            if n.pins <= 0:
                raise RuntimeError(
                    f"unpin of unpinned radix node (depth "
                    f"{n.depth_tokens} tokens)"
                )
            n.pins -= 1
        for n in sorted(nodes, key=lambda x: -x.depth_tokens):
            if (
                n.version != self.version
                and n.pins == 0
                and not n.children
                and n.parent is not None
            ):
                self._evict_node(n)

    def _evict_node(self, node: RadixNode) -> None:
        del node.parent.children[node.key]
        self.pool.decref([node.block_id])
        node.parent = None
        self._n_nodes -= 1
        self.evicted_blocks_total += 1

    def _evictable_leaves(self) -> list[RadixNode]:
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.pins == 0:
                out.append(n)
        return out

    def evictable_blocks(self) -> int:
        """Blocks the cache could eventually release: nodes in subtrees
        with no pinned descendant (introspection/tests; iterative — cached
        chains are as deep as blocks-per-sequence, far past the recursion
        limit for long-context configs)."""
        # post-order via explicit stack: a node is evictable iff it is
        # unpinned AND every descendant is evictable
        clean: dict[int, bool] = {}
        count = 0
        stack: list[tuple[RadixNode, bool]] = [(self._root, False)]
        while stack:
            node, visited = stack.pop()
            if not visited:
                stack.append((node, True))
                for c in node.children.values():
                    stack.append((c, False))
                continue
            ok = node.pins == 0 and all(
                clean[id(c)] for c in node.children.values()
            )
            clean[id(node)] = ok
            if ok and node is not self._root:
                count += 1
        return count

    def evict(self, n_blocks: int) -> int:
        """Evict up to ``n_blocks`` unpinned blocks, LRU leaves first
        (walking up as parents become leaves). Returns how many were
        actually released to the pool."""
        freed = 0
        while freed < n_blocks:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_use)
            for leaf in leaves:
                if freed >= n_blocks:
                    break
                parent = leaf.parent
                self._evict_node(leaf)
                freed += 1
                # walk upward while the parent just became an evictable
                # leaf AND is older than other candidates — cheap
                # approximation: only continue upward inside this pass if
                # the parent is unpinned and childless
                while (
                    freed < n_blocks
                    and parent is not None
                    and parent is not self._root
                    and parent.pins == 0
                    and not parent.children
                ):
                    nxt = parent.parent
                    self._evict_node(parent)
                    freed += 1
                    parent = nxt
        return freed

    def _evict_matching(self, pred) -> int:
        """One post-order pass (children before parents, so a parent whose
        whole subtree evicts becomes childless within the SAME pass):
        evict every node that satisfies ``pred``, is unpinned, and has no
        surviving children. O(N) — this runs on the engine thread inside
        the weight-commit window, where a repeated leaf-scan loop would
        cost O(N·depth) and inflate weight_sync_stall_seconds."""
        freed = 0
        stack: list[tuple[RadixNode, bool]] = [(self._root, False)]
        while stack:
            node, visited = stack.pop()
            if not visited:
                stack.append((node, True))
                for c in node.children.values():
                    stack.append((c, False))
                continue
            if (
                node is not self._root
                and node.pins == 0
                and not node.children
                and pred(node)
            ):
                self._evict_node(node)
                freed += 1
        return freed

    def on_weights_changed(self, new_version: int) -> int:
        """Weight-version fence: bump the cache's version and evict every
        unpinned stale node NOW (pinned ones are reaped by ``unpin``).
        Called on the engine thread right after a commit so a new-version
        prefill can never splice stale-version blocks. Returns the number
        of blocks released."""
        self.version = int(new_version)
        return self._evict_matching(lambda n: n.version != self.version)

    def flush(self) -> int:
        """Drop every unpinned node regardless of version (tests,
        defensive resets). Returns blocks released."""
        return self._evict_matching(lambda n: True)

    def check_invariants(self) -> None:
        """Every cached block must hold at least the cache's own pool
        reference, and the node count must match the tree."""
        count = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            count += 1
            if self.pool.ref[n.block_id] <= 0:
                raise RuntimeError(
                    f"radix node holds freed block {n.block_id}"
                )
            stack.extend(n.children.values())
        if count != self._n_nodes:
            raise RuntimeError(
                f"radix node count {self._n_nodes} != tree walk {count}"
            )
