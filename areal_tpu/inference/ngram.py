"""Host-side n-gram draft proposer for draft-free speculative decoding.

The vLLM/SGLang "prompt lookup" idea: RL reasoning/math completions repeat
themselves (restated problem text, recurring equation fragments, greedy
attractor cycles), so the cheapest draft model is the sequence's OWN
history — match the trailing n-gram of prompt+output against earlier
positions and propose the tokens that followed the most recent match. No
second model, no extra device memory; proposal cost is a few numpy
window comparisons per slot per window.
"""

from __future__ import annotations

import numpy as np

# Proposal scans only the trailing MAX_SCAN tokens of a sequence's history:
# per-window cost stays bounded as sequences grow (a full-history scan per
# slot per window is O(L^2) over a generation and would creep into the
# engine loop's host budget at long context). Repetition useful to a
# lookahead draft is overwhelmingly local, so distant matches are a poor
# trade for the scan cost.
MAX_SCAN = 2048


def ngram_propose(
    history: list[int] | np.ndarray,
    min_n: int,
    max_n: int,
    draft_len: int,
    max_scan: int = MAX_SCAN,
) -> list[int]:
    """Propose up to ``draft_len`` continuation tokens for ``history``.

    Tries suffix n-grams longest-first (``n = max_n .. min_n``): if the
    last ``n`` tokens also occur earlier in ``history`` (with at least one
    token following the occurrence), return the tokens after the most
    recent occurrence that still has a FULL ``draft_len`` continuation
    (recency tracks local repetition structure — loops, restated spans —
    and a full window maximizes tokens verified per dispatch; matches so
    late that the continuation would run off the end of history are used
    only when nothing better exists). Returns ``[]`` when nothing matches;
    callers fall back to plain decode.
    """
    arr = np.asarray(history, dtype=np.int64)
    if max_scan and arr.size > max_scan:
        arr = arr[-max_scan:]
    h = arr.size
    if draft_len <= 0 or h < min_n + 1 or min_n < 1:
        return []
    for n in range(min(max_n, h - 1), min_n - 1, -1):
        suffix = arr[h - n:]
        # windows over arr[:h-1]: start j in [0, h-1-n], so every match
        # has a continuation token at j+n and the suffix itself (j = h-n)
        # is excluded
        windows = np.lib.stride_tricks.sliding_window_view(arr[: h - 1], n)
        matches = np.flatnonzero((windows == suffix).all(axis=1))
        if matches.size:
            full = matches[matches + n + draft_len <= h]
            j = int(full[-1]) if full.size else int(matches[-1])
            return arr[j + n : j + n + draft_len].tolist()
    return []
