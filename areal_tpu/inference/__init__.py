"""TPU-native continuous-batching generation service.

Replaces the reference's SGLang/vLLM servers + the 538-line SGLang patch
(patch/sglang/v0.5.2.patch, SURVEY §2.1): a JetStream-style JAX inference
engine with slot-based continuous batching, interruptible generation
(abort + client re-issue), per-token weight-version tagging, and in-place
weight refresh from disk.
"""

from areal_tpu.inference.engine import GenerationEngine  # noqa: F401
from areal_tpu.inference.prefix_cache import RadixPrefixCache  # noqa: F401
from areal_tpu.inference.scheduler import AdmissionScheduler  # noqa: F401
