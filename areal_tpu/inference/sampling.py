"""On-device token sampling for the generation engine.

The reference delegates sampling to SGLang's CUDA sampler; here it is a pure
jittable function fused into the prefill/decode calls so logits never leave
the device. Logprobs are computed under the *modified* (temperature / top-k /
top-p) distribution — the true behavior-policy logprob that decoupled PPO
consumes (reference: SGLang `output_token_logprobs`,
areal/engine/sglang_remote.py:22-170).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _apply_top_k_top_p(
    scaled: jnp.ndarray, top_k: jnp.ndarray, top_p: jnp.ndarray
) -> jnp.ndarray:
    """Fused top-k + nucleus filtering sharing ONE descending sort.

    Matches sequential top-k-then-top-p semantics (the SGLang convention the
    reference relies on): the nucleus mass is computed on the top-k-filtered,
    RENORMALIZED distribution. The highest-probability token always survives
    (exclusive-cumulative test)."""
    b, v = scaled.shape
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    pos = jnp.arange(v)[None, :]
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    keep_k = pos < k[:, None]
    probs = jax.nn.softmax(
        jnp.where(keep_k, sorted_logits, _NEG_INF), axis=-1
    )  # renormalized over the surviving top-k
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]
    keep_sorted = keep_k & keep_p
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(keep_sorted)
    return jnp.where(keep, scaled, _NEG_INF)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] fp32
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] fp32 (1.0 = off)
    greedy: jnp.ndarray,  # [B] bool
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens [B] int32, logprobs [B] fp32).

    The filter knobs are fully DYNAMIC: one compiled program regardless of
    the batch's top-k/top-p mixture (the round-1 engine flipped static args
    per batch, recompiling on mixture changes). A runtime ``lax.cond`` skips
    the vocab sort entirely when every row has both filters disabled."""
    scaled = _modified_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    argmax = jnp.argmax(scaled, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    logp_dist = jax.nn.log_softmax(scaled, axis=-1)
    logprobs = jnp.take_along_axis(logp_dist, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs


def _modified_logits(
    logits: jnp.ndarray,  # [B, V]
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Temperature + top-k/top-p filtered logits — the MODIFIED behavior
    distribution every sampled/recorded token lives under (shared by the
    plain sampler and the speculative verifier, so the two can never
    diverge on what 'the policy' is)."""
    scaled = logits / jnp.maximum(temperature, 1e-5)[:, None]
    need = jnp.any(top_k > 0) | jnp.any(top_p < 1.0)
    return jax.lax.cond(
        need,
        lambda s: _apply_top_k_top_p(s, top_k, top_p),
        lambda s: s,
        scaled,
    )


def spec_verify_tokens(
    logits: jnp.ndarray,  # [B, K+1, V] fp32 per-position verify logits
    draft: jnp.ndarray,  # [B, K] int32 proposed tokens (pad past draft_len)
    draft_len: jnp.ndarray,  # [B] int32 valid draft count per row (0..K)
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    greedy: jnp.ndarray,  # [B] bool
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative acceptance over one verify window.

    ``logits[:, t]`` is the target model's next-token distribution after
    consuming the fed prefix ``[last_token, draft_0..draft_{t-1}]`` (the
    multi-token verify dispatch). Returns ``(tokens [B, K+1],
    logprobs [B, K+1], n_accepted [B])``; row ``b`` emits exactly
    ``tokens[b, : n_accepted[b] + 1]`` — the accepted draft prefix plus one
    extra token (the rejection-position correction, or the bonus token when
    every valid draft was accepted). Positions past that are garbage.

    Acceptance preserves the served policy EXACTLY:

    - greedy rows accept draft_t iff it equals the argmax of the modified
      logits at position t, and emit the argmax at the first mismatch —
      so spec-on output is token-identical to spec-off greedy decode;
    - sampled rows run rejection sampling against the deterministic n-gram
      proposal (q = one-hot at draft_t): accept with probability
      p(draft_t); on rejection sample from the residual
      ``norm(max(p - q, 0))`` = p with the draft token removed. The
      emitted tokens are then distributed exactly as ancestral sampling
      from the modified distribution p.

    Per-token logprobs are ``log p(token)`` under the modified
    distribution — the same behavior-policy quantity the plain sampler
    records, which is what decoupled-PPO importance ratios consume.

    Rows with ``draft_len == 0`` behave exactly like a plain decode step:
    position 0 is a plain sample/argmax and ``n_accepted == 0``.
    """
    b, k1, v = logits.shape
    k = k1 - 1
    rng_accept, rng_fallback = jax.random.split(rng)
    rep = lambda x: jnp.repeat(x, k1, axis=0)  # noqa: E731 — [B] -> [B*K1]
    scaled = _modified_logits(
        logits.reshape(b * k1, v), rep(temperature), rep(top_k), rep(top_p)
    ).reshape(b, k1, v)
    logp_dist = jax.nn.log_softmax(scaled, axis=-1)
    argmax_tok = jnp.argmax(scaled, axis=-1).astype(jnp.int32)  # [B, K+1]

    # accept tests on the K draft positions
    p_draft = jnp.exp(
        jnp.take_along_axis(
            logp_dist[:, :k], draft[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
    )  # [B, K]
    unif = jax.random.uniform(rng_accept, (b, k))
    accept = jnp.where(
        greedy[:, None], draft == argmax_tok[:, :k], unif < p_draft
    )
    valid = jnp.arange(k)[None, :] < draft_len[:, None]
    accept = accept & valid
    # leading run of accepts (a rejection kills everything after it)
    n_accepted = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
    )  # [B] in 0..draft_len

    # fallback token per position: the residual sample at rejected draft
    # positions (draft token zeroed out of p, renormalized by categorical),
    # a PLAIN sample at positions without a valid draft (the bonus token
    # after a fully-accepted window, and position 0 of draft-less rows)
    draft_pad = jnp.concatenate(
        [draft.astype(jnp.int32), jnp.zeros((b, 1), jnp.int32)], axis=1
    )  # [B, K+1]
    valid_pad = jnp.concatenate([valid, jnp.zeros((b, 1), bool)], axis=1)
    cur = jnp.take_along_axis(scaled, draft_pad[..., None], axis=-1)[..., 0]
    masked = scaled.at[
        jnp.arange(b)[:, None], jnp.arange(k1)[None, :], draft_pad
    ].set(jnp.where(valid_pad, _NEG_INF, cur))
    fallback = jnp.where(
        greedy[:, None],
        argmax_tok,
        jax.random.categorical(rng_fallback, masked, axis=-1).astype(
            jnp.int32
        ),
    )
    pos = jnp.arange(k1)[None, :]
    tokens = jnp.where(pos < n_accepted[:, None], draft_pad, fallback)
    logprobs = jnp.take_along_axis(
        logp_dist, tokens[..., None], axis=-1
    )[..., 0]
    return tokens, logprobs, n_accepted
