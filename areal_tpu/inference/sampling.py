"""On-device token sampling for the generation engine.

The reference delegates sampling to SGLang's CUDA sampler; here it is a pure
jittable function fused into the prefill/decode calls so logits never leave
the device. Logprobs are computed under the *modified* (temperature / top-k /
top-p) distribution — the true behavior-policy logprob that decoupled PPO
consumes (reference: SGLang `output_token_logprobs`,
areal/engine/sglang_remote.py:22-170).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _apply_top_k_top_p(
    scaled: jnp.ndarray, top_k: jnp.ndarray, top_p: jnp.ndarray
) -> jnp.ndarray:
    """Fused top-k + nucleus filtering sharing ONE descending sort.

    Matches sequential top-k-then-top-p semantics (the SGLang convention the
    reference relies on): the nucleus mass is computed on the top-k-filtered,
    RENORMALIZED distribution. The highest-probability token always survives
    (exclusive-cumulative test)."""
    b, v = scaled.shape
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    pos = jnp.arange(v)[None, :]
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    keep_k = pos < k[:, None]
    probs = jax.nn.softmax(
        jnp.where(keep_k, sorted_logits, _NEG_INF), axis=-1
    )  # renormalized over the surviving top-k
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]
    keep_sorted = keep_k & keep_p
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(keep_sorted)
    return jnp.where(keep, scaled, _NEG_INF)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] fp32
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] fp32 (1.0 = off)
    greedy: jnp.ndarray,  # [B] bool
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens [B] int32, logprobs [B] fp32).

    The filter knobs are fully DYNAMIC: one compiled program regardless of
    the batch's top-k/top-p mixture (the round-1 engine flipped static args
    per batch, recompiling on mixture changes). A runtime ``lax.cond`` skips
    the vocab sort entirely when every row has both filters disabled."""
    scaled = logits / jnp.maximum(temperature, 1e-5)[:, None]
    need = jnp.any(top_k > 0) | jnp.any(top_p < 1.0)
    scaled = jax.lax.cond(
        need,
        lambda s: _apply_top_k_top_p(s, top_k, top_p),
        lambda s: s,
        scaled,
    )
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    argmax = jnp.argmax(scaled, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    logp_dist = jax.nn.log_softmax(scaled, axis=-1)
    logprobs = jnp.take_along_axis(logp_dist, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs
