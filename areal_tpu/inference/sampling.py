"""On-device token sampling for the generation engine.

The reference delegates sampling to SGLang's CUDA sampler; here it is a pure
jittable function fused into the prefill/decode calls so logits never leave
the device. Logprobs are computed under the *modified* (temperature / top-k /
top-p) distribution — the true behavior-policy logprob that decoupled PPO
consumes (reference: SGLang `output_token_logprobs`,
areal/engine/sglang_remote.py:22-170).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _apply_top_k(scaled: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the per-row top-k. top_k [B] int32, 0 = disabled."""
    v = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    thresh = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [B,1]
    return jnp.where(scaled >= thresh, scaled, _NEG_INF)


def _apply_top_p(scaled: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering. top_p [B] float32, 1.0 = disabled.

    Keeps the smallest prefix of probability-sorted tokens whose cumulative
    mass reaches top_p (the highest-probability token always survives).
    """
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept if the cumulative mass *before* it is < top_p
    keep_sorted = (cum - probs) < top_p[:, None]
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(scaled.shape[0])[:, None], sort_idx
    ].set(keep_sorted)
    return jnp.where(keep, scaled, _NEG_INF)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] fp32
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] fp32 (1.0 = off)
    greedy: jnp.ndarray,  # [B] bool
    use_top_k: bool = True,  # static: compile out the sort when unused
    use_top_p: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens [B] int32, logprobs [B] fp32)."""
    scaled = logits / jnp.maximum(temperature, 1e-5)[:, None]
    if use_top_k:
        scaled = _apply_top_k(scaled, top_k)
    if use_top_p:
        scaled = _apply_top_p(scaled, top_p)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    argmax = jnp.argmax(scaled, axis=-1)
    tokens = jnp.where(greedy, argmax, sampled).astype(jnp.int32)
    logp_dist = jax.nn.log_softmax(scaled, axis=-1)
    logprobs = jnp.take_along_axis(logp_dist, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs
