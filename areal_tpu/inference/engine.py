"""Slot-based continuous-batching generation engine.

This is the TPU-native replacement for the SGLang/vLLM server internals the
reference leans on (patch/sglang/v0.5.2.patch + areal/launcher/sglang_server.py,
SURVEY §2.1, §7 step 4). Capabilities:

- **Continuous batching**: a fixed pool of ``max_batch_size`` KV-cache slots;
  finished requests free their slot and queued requests are admitted without
  draining the batch. All jitted shapes are static (TPU/XLA requirement);
  prompt lengths round up to buckets, decode runs ``decode_steps_per_call``
  tokens per dispatch for all slots at once.
- **Interruptible generation** (reference remote_inf_engine.py:424-474 server
  side): ``pause()`` aborts every in-flight request, returning partial output
  with ``stop_reason="abort"``; the client re-issues with accumulated tokens.
- **In-place weight refresh**: ``update_weights_from_disk`` loads a safetensors
  checkpoint into the live sharded params between decode dispatches and bumps
  the engine version; every generated token is tagged with the version that
  produced it (ModelResponse.output_versions).
- **TP sharding**: params/caches laid out on a ("pp","dp","cp","tp") mesh with
  ``tp_size`` devices on the tp axis; GSPMD inserts the collectives.

Host-side state (slot table, per-request accumulators) is plain numpy; device
state is (params, kv_cache) only — both donated through the jitted steps so
HBM holds exactly one copy.
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters, JaxGenConfig
from areal_tpu.api.io_struct import ModelResponse
from areal_tpu.models import hf_io
from areal_tpu.models.config import TransformerConfig, from_hf_config
from areal_tpu.models.lm import (
    decode_step_paged,
    init_paged_kv_cache,
    init_params,
    prefill_stream,
    spec_verify_step_paged,
    write_prefill_blocks,
)
from areal_tpu.inference.block_pool import (
    TRASH_BLOCK,
    BlockPool,
    OutOfBlocks,
)
from areal_tpu.inference.ngram import MAX_SCAN, ngram_propose
from areal_tpu.inference.prefix_cache import RadixPrefixCache
from areal_tpu.inference.sampling import sample_tokens, spec_verify_tokens
from areal_tpu.inference.scheduler import AdmissionScheduler
from areal_tpu.parallel.mesh import MESH_AXES, AXIS_PP, AXIS_TP
from areal_tpu.parallel.sharding import param_shardings
from areal_tpu.utils import logging

logger = logging.getLogger("GenerationEngine")

_PAD = 0


@dataclasses.dataclass
class _Seq:
    """One in-flight request bound to a cache slot."""

    rid: str
    prompt: list[int]
    gconfig: GenerationHyperparameters
    on_done: Callable[[ModelResponse], None]
    slot: int = -1
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    out_logprobs: list[float] = dataclasses.field(default_factory=list)
    out_versions: list[int] = dataclasses.field(default_factory=list)
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    t_first_token: float | None = None
    t_last_token: float | None = None
    itl: list[float] = dataclasses.field(default_factory=list)
    aborted: bool = False
    priority: int = 0  # admission priority (higher admits first)
    # distributed-tracing span (utils/tracing.Span) owned by the server
    # handler; None when tracing is off — every engine-side use guards
    # with `is not None` (zero allocation off, code-inspection-pinned)
    span: object | None = None
    images: list | None = None  # decoded [S, S, 3] float arrays, or for
    # qwen2_vl: HF-processor patch arrays [P_i, C*tps*ps*ps]
    grids: list | None = None  # qwen2_vl (t, h, w) per image
    # the scheduler entry this seq was popped with: preemption hands it
    # back via push_front so the victim requeues at its ORIGINAL position
    sched_entry: dict | None = None
    # disaggregated serving: a prefill-role request finishes with its KV
    # retained PINNED (exportable via export_kv) instead of merely cached
    prefill_only: bool = False
    # TTFT phase decomposition: when this request left the admission queue
    # (engine loop), and whether it admitted via prefill or a retained-KV
    # resume (an imported-KV sequence's first token is a DECODE step — its
    # latency is attributed to the first_decode phase, not prefill)
    t_admitted: float | None = None
    admitted_via_resume: bool = False
    # adaptive speculative drafting: per-sequence acceptance EWMA and the
    # draft length it currently maps to (0 = not yet initialized; the
    # static config value applies)
    spec_ewma: float = 1.0
    spec_k: int = 0

    @property
    def max_total(self) -> int:
        return len(self.prompt) + self.gconfig.max_new_tokens

    def stop_ids(self, eos_token_id: int | None) -> set[int]:
        s = set(self.gconfig.stop_token_ids)
        if eos_token_id is not None:
            s.add(eos_token_id)
        return s


@dataclasses.dataclass
class _Retained:
    """Retained KV for one interrupted/aborted/preempted rid: ``slot``'s
    cache rows [0, len(covered)) hold the K/V of ``covered``; ``feed_tok``
    is the next token to feed decode (its row is written when fed).

    ``version`` tags the weight version the OWNING sequence last decoded
    under — a resume that finds ``version != engine.version`` crossed a
    staged commit and continues on the NEW weights (accepted staleness;
    per-token ``versions`` record the crossing for decoupled PPO).
    ``pinned`` entries (explicit interrupts, scheduler preemptions) are
    evicted only as a last resort; plain abort retention goes first."""

    slot: int
    covered: tuple
    feed_tok: int
    ts: float
    version: int
    pinned: bool = False


class KVVersionMismatch(Exception):
    """A KV import carried blocks computed under a different weight version
    than this engine serves — spliced in they would mix attention state
    across a commit, exactly what the radix admission fence forbids. The
    server maps this to HTTP 412; the client falls back to a local full
    prefill (loud, counted, never silent)."""


class KVNoCapacity(Exception):
    """A KV import could not get a free slot or enough pool blocks even
    after the eviction ladder. Mapped to HTTP 503; the client falls back to
    a local full prefill on this server (which queues like any admission)."""


class GenerationEngine:
    """In-process generation engine; the HTTP server and colocated rollout
    engines both drive this object."""

    def __init__(
        self,
        config: JaxGenConfig,
        model_config: TransformerConfig | None = None,
        params: Any | None = None,
        tokenizer: Any | None = None,
        devices: list | None = None,
    ):
        # own copy: init may round max_batch_size up to a pp multiple, and
        # a config object shared with capacity formulas or a second engine
        # must not change value underneath the caller
        config = dataclasses.replace(config)
        self.config = config
        if config.jax_compilation_cache_dir:
            # before ANY jit below: a relaunched server (PR 4 preemption
            # plane) reloads its decode/prefill executables instead of
            # paying full recompile
            from areal_tpu.utils.jax_cache import configure_compilation_cache

            configure_compilation_cache(config.jax_compilation_cache_dir)
        self.tokenizer = tokenizer
        devices = devices if devices is not None else jax.devices()
        tp, pp = config.tp_size, config.pp_size
        if len(devices) < tp * pp:
            raise ValueError(
                f"tp_size={tp} x pp_size={pp} but only {len(devices)} devices"
            )
        self._pp = pp
        self.mesh = jax.sharding.Mesh(
            np.asarray(devices[: tp * pp]).reshape(pp, 1, 1, tp), MESH_AXES
        )

        if model_config is None:
            if not config.model_path:
                raise ValueError("need model_config or config.model_path")
            model_config = from_hf_config(config.model_path)
        self.model_config = model_config
        if pp > 1:
            if model_config.num_hidden_layers % pp:
                raise ValueError(
                    f"pp_size={pp} must divide num_hidden_layers="
                    f"{model_config.num_hidden_layers}"
                )
            if config.max_batch_size % pp and config.pp_rotate_decode:
                # batch-group rotation (decode_rotated_pp) needs the decode
                # bucket divisible by pp; round the slot count up so the
                # S x-faster path is always eligible
                new_b = -(-config.max_batch_size // pp) * pp
                logger.info(
                    "rounding max_batch_size %d up to %d (multiple of "
                    "pp_size=%d) so rotated pp-decode stays eligible",
                    config.max_batch_size, new_b, pp,
                )
                config.max_batch_size = new_b
        if config.prefill_chunk_size > 0:
            # preferred serving-plane name; both knobs drive the same
            # intra-prompt chunked-prefill machinery (engine's own copy)
            config.chunked_prefill_tokens = config.prefill_chunk_size
        if config.role not in ("", "prefill", "decode"):
            raise ValueError(
                f"role must be ''|'prefill'|'decode', got {config.role!r}"
            )
        if config.role == "decode" and config.chunked_prefill_tokens > 0:
            # decode-role engines skip chunked-prefill interleaving
            # entirely: their steady-state work is imported-KV decode, and
            # the rare fallback full prefill (refused import) should
            # dispatch whole-prompt rather than trickle chunks between
            # decode iterations keeping batches ragged
            logger.info(
                "role='decode': disabling chunked-prefill interleaving "
                "(chunked_prefill_tokens %d -> 0; dense decode batches)",
                config.chunked_prefill_tokens,
            )
            config.chunked_prefill_tokens = 0
        requested_s = config.max_seq_len
        blk = min(config.page_size, config.max_seq_len)
        if config.max_seq_len % blk:
            # page-align on the engine's own copy (same treatment as the
            # pp batch rounding) — callers must not have to hand-roll KV
            # page alignment. Before the position-window checks below so
            # the rounded-up value is what gets validated.
            new_s = -(-config.max_seq_len // blk) * blk
            logger.info(
                "rounding max_seq_len %d up to %d (multiple of the KV "
                "block size %d; knob: page_size)",
                config.max_seq_len, new_s, blk,
            )
            config.max_seq_len = new_s

        def _rounding_note() -> str:
            # a window error must blame the right knob: if only the PAGE
            # ROUNDING pushed past the window, the fix is a page_size that
            # divides the window, not a smaller request
            if config.max_seq_len == requested_s:
                return ""
            return (
                f" (requested max_seq_len={requested_s} was page-aligned "
                f"up to {config.max_seq_len}; a page_size dividing "
                f"{requested_s} would avoid the round-up)"
            )

        if (
            model_config.pos_embed_type == "learned"
            and config.max_seq_len > model_config.max_position_embeddings
        ):
            # gather clamps out-of-range rows silently; fail loudly instead
            raise ValueError(
                f"max_seq_len={config.max_seq_len} exceeds the learned "
                f"position table ({model_config.max_position_embeddings})"
                + _rounding_note()
            )
        if (
            model_config.rope_scaling_type == "dynamic"
            and config.max_seq_len > model_config.max_position_embeddings
        ):
            # dynamic NTK matches HF exactly only INSIDE the trained window
            # (beyond it HF re-stretches the base per sequence length, which
            # a static compiled schedule cannot) — serving past the window
            # would silently diverge
            raise ValueError(
                f"max_seq_len={config.max_seq_len} exceeds "
                f"max_position_embeddings "
                f"({model_config.max_position_embeddings}) on a dynamic-NTK "
                "rope model; extension beyond the trained window is not "
                "supported" + _rounding_note()
            )

        # per-engine attention dispatch (no process-global state): under TP,
        # prefill keeps the Pallas flash kernel with heads sharded over the
        # tp axis via shard_map; decode stays on the GSPMD einsum path
        from areal_tpu.ops.attention import AttnSpec

        self.attn_spec = AttnSpec.for_mesh(
            self.mesh, model_config, token_axes=(), head_axis=AXIS_TP
        )
        # Pallas serving-kernel fallback ledger: (site, reason) -> count.
        # Every config that *asked* for a kernel but serves XLA instead is
        # counted here and exported as pallas_fallback_total{site,reason}
        # via metrics_snapshot() — the fleet being silently off the fast
        # path is a scrapeable number, not a log line lost at init.
        self.pallas_fallbacks: dict[tuple[str, str], int] = {}
        # kernel-tier serving attention (ops/pallas/): the ragged paged
        # decode kernel and the chunked-prefill flash kernel, both walking
        # the block table in place; int8 pools dequantize in-kernel, so
        # kv_quant composes with either knob. A raw pallas_call has no
        # GSPMD partitioning rule, so TP-sharded serving stays on the
        # einsum path — falling back LOUDLY (one-shot structured warning +
        # counter), never silently serving a different kernel than asked.
        kernel_impl = (
            "pallas" if jax.default_backend() == "tpu" else "pallas_interpret"
        )
        if config.use_pallas_decode:
            if config.tp_size > 1:
                self._note_pallas_fallback("decode", "tp_size")
            else:
                self.attn_spec = dataclasses.replace(
                    self.attn_spec, decode_impl=kernel_impl
                )
        if config.use_pallas_prefill:
            if config.tp_size > 1:
                self._note_pallas_fallback("prefill", "tp_size")
            else:
                self.attn_spec = dataclasses.replace(
                    self.attn_spec, prefill_impl=kernel_impl
                )
        self.dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32

        shape_tree = jax.eval_shape(
            lambda: init_params(model_config, jax.random.PRNGKey(0), self.dtype)
        )
        self._shardings = param_shardings(self.mesh, shape_tree, fsdp=False)
        if params is not None:
            self.params = jax.device_put(params, self._shardings)
        elif config.model_path:
            self.params = self._load_params_from(config.model_path)
        else:
            with jax.default_device(devices[0]):
                raw = init_params(
                    model_config, jax.random.PRNGKey(config.random_seed), self.dtype
                )
            self.params = jax.device_put(raw, self._shardings)

        b, s = config.max_batch_size, config.max_seq_len
        # Paged KV pool (the SGLang paged-allocator role,
        # patch/sglang/v0.5.2.patch): HBM holds `kv_pool_tokens` worth of
        # fixed-size blocks shared by all slots via per-slot block tables,
        # instead of a dense [B, max_seq] reservation per slot.
        self.block_size = min(config.page_size, s)
        assert s % self.block_size == 0  # rounded at init
        pool_tokens = config.kv_pool_tokens or b * s
        self.max_blocks_per_seq = s // self.block_size
        num_blocks = -(-pool_tokens // self.block_size) + 1  # +1 trash block
        if num_blocks - 1 < self.max_blocks_per_seq:
            raise ValueError(
                f"kv_pool_tokens={pool_tokens} cannot hold even one "
                f"max_seq_len={s} sequence"
            )
        self.pool = BlockPool(num_blocks, self.block_size)
        # Radix prefix cache (inference/prefix_cache.py): full KV blocks of
        # finished sequences stay matchable under their token prefix even
        # after the slot is re-prefilled — the cross-slot generalization of
        # the slot-level clone/extension reuse below. Version-fenced on
        # every weight commit.
        self.prefix_cache: RadixPrefixCache | None = (
            RadixPrefixCache(self.pool)
            if config.enable_prefix_cache
            else None
        )
        if config.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant must be none|int8, got {config.kv_quant!r}"
            )
        if config.spec_decode not in ("none", "ngram"):
            raise ValueError(
                f"spec_decode must be none|ngram, got {config.spec_decode!r}"
            )
        if config.spec_decode == "ngram":
            # fail loudly: a silently-empty proposer range would pay the
            # per-window proposal scan forever while spec_acceptance_rate
            # reads 0.0 with no hint why
            if config.spec_draft_len < 1:
                raise ValueError(
                    f"spec_draft_len must be >= 1 with spec_decode='ngram',"
                    f" got {config.spec_draft_len}"
                )
            if not 1 <= config.spec_ngram_min <= config.spec_ngram_max:
                raise ValueError(
                    "need 1 <= spec_ngram_min <= spec_ngram_max, got "
                    f"min={config.spec_ngram_min} max={config.spec_ngram_max}"
                )
            if config.spec_draft_len_max > config.spec_draft_len:
                # the verify dispatch's static window is spec_draft_len
                # wide; growing a slot's draft past it would need a wider
                # compiled program — make the config contradiction loud
                raise ValueError(
                    f"spec_draft_len_max={config.spec_draft_len_max} "
                    f"exceeds the static verify window spec_draft_len="
                    f"{config.spec_draft_len}; raise spec_draft_len instead"
                )
        self._spec_enabled = config.spec_decode == "ngram"
        # adaptive per-sequence draft length (EWMA of the slot's OWN
        # acceptance): bounds [spec_draft_len_min, max], where max=0 means
        # "= spec_draft_len". min=0 disables adaptation (static drafting).
        self._spec_draft_max = (
            config.spec_draft_len_max or config.spec_draft_len
        )
        self._spec_draft_min = min(
            config.spec_draft_len_min, self._spec_draft_max
        )
        self._spec_adaptive = (
            self._spec_enabled
            and self._spec_draft_min >= 1
            and self._spec_draft_min < self._spec_draft_max
        )
        if self._spec_enabled and pp > 1:
            # the pp decode conveyors (sequential + rotated) are single-
            # token-per-tick machines; verify windows are not threaded
            # through them yet
            logger.warning(
                "spec_decode='ngram' is not wired through pp decode "
                "(pp_size=%d); falling back to non-speculative decode", pp
            )
            self._spec_enabled = False
        # speculative-decoding counters (surfaced via server /model_info):
        # acceptance rate = accepted / proposed; each window also emits one
        # non-drafted token (the correction/bonus), so emitted tokens per
        # dispatch = mean(n_accepted) + 1
        self.spec_steps_total = 0
        self.spec_proposed_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        cache = init_paged_kv_cache(
            model_config, num_blocks, self.block_size, self.dtype,
            quant=config.kv_quant,
        )
        kh_div = model_config.num_key_value_heads % tp == 0
        cache_spec = jax.sharding.PartitionSpec(
            AXIS_PP if pp > 1 else None,  # pool's L dim lives per stage
            None, None,
            AXIS_TP if kh_div else None,
            None,
        )
        self._cache_sharding = jax.sharding.NamedSharding(self.mesh, cache_spec)
        # int8 scale planes [L, NB, BS, KH] shard like the pools minus D
        scale_sharding = jax.sharding.NamedSharding(
            self.mesh,
            jax.sharding.PartitionSpec(
                AXIS_PP if pp > 1 else None,
                None, None,
                AXIS_TP if kh_div else None,
            ),
        )
        self.cache = jax.device_put(
            cache,
            {
                k: (self._cache_sharding if k in ("k", "v") else scale_sharding)
                for k in cache
            },
        )
        # KV-pool memory gauge (serving_stats kv_pool_*): static byte
        # accounting off the pool's shapes/dtypes, so the int8 memory win
        # (quantized rows + f32 scale overhead vs fp rows) is a scrapeable
        # number, not a claim
        self._kv_pool_dtype = str(cache["k"].dtype)
        self._kv_pool_kv_bytes = int(cache["k"].nbytes) + int(
            cache["v"].nbytes
        )
        self._kv_pool_scale_bytes = sum(
            int(cache[k].nbytes) for k in ("ks", "vs") if k in cache
        )
        # per-slot block tables (-1 = unmapped) + valid-entry counts
        self.block_table = np.full((b, self.max_blocks_per_seq), -1, np.int32)
        self._slot_nblocks = np.zeros(b, np.int64)
        self._slot_last_use = np.zeros(b, np.float64)

        self._rng_base = jax.random.PRNGKey(config.random_seed)
        self._rng_counter = 0

        # host slot table
        self.cache_len = np.zeros(b, np.int32)
        self.slots: list[_Seq | None] = [None] * b
        self.last_token = np.zeros(b, np.int32)
        # qwen2_vl M-RoPE decode delta per slot: rope position = cache_len +
        # delta (image placeholder runs occupy fewer rope positions than
        # cache rows; 0 for text / non-mrope models)
        self.pos_delta = np.zeros(b, np.int32)
        self.version = 0

        # control plane: prioritized admission queue + token-budget
        # admission control (inference/scheduler.py). Budget 0 derives
        # from pool capacity — the pool's token count IS what it can hold.
        budget = config.admission_token_budget
        if budget <= 0:
            budget = (num_blocks - 1) * self.block_size
        self.scheduler = AdmissionScheduler(token_budget=budget)
        self._cmd_queue: queue.Queue = queue.Queue()
        self._paused = threading.Event()
        self._shutdown = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._abort_rids: set[str] = set()  # guarded_by: _lock
        # token-boundary interruption (interrupt()/interrupt_all()): rid ->
        # reason, swapped out by the engine thread between decode chunks —
        # the interrupted sequence answers with stop_reason="interrupt" and
        # its KV stays retained (pinned) for the resume path
        self._interrupt_rids: dict[str, str] = {}  # guarded_by: _lock
        # Pipelined weight sync: chunks are STAGED off the engine thread
        # (device_put onto the live leaves' shardings, no touch of
        # self.params) while decode dispatches continue; the engine thread
        # only runs the final pointer-flip commit. _staged_leaves maps
        # dotted path -> placed jax.Array; _staging_version tags which
        # update the staged set belongs to, so a torn stream's leftovers
        # are superseded (abandoned) by the next update instead of
        # corrupting it.
        self._staged_leaves: dict[str, Any] = {}  # guarded_by: _staging_lock
        self._staging_version: int | None = None  # guarded_by: _staging_lock
        self._staging_lock = threading.Lock()
        # adapter-native serving: pristine base params retained across
        # adapter-only updates (None until the first /update_lora_weights)
        self._lora_base = None
        # KV retention across interrupt/abort-resume (VERDICT r1 weak #4):
        # rid -> _Retained (slot, covered tokens, next feed token, ts,
        # weight version at retention, pin). The client's interrupt loop
        # re-issues prompt+accumulated; an exact match resumes decode with
        # ZERO re-prefill, and a longer re-issue that still extends the
        # covered prefix recomputes ONLY the uncovered suffix. Survives
        # weight updates by design: per-token versions still record the
        # sampling policy and the trainer recomputes exact logprobs
        # (decoupled PPO), while the retained attention state is an
        # accepted staleness (knob: JaxGenConfig.retain_kv_on_abort).
        # _retained_lock is a LEAF lock: held only around map reads/writes,
        # never across calls that take _lock, _staging_lock, or the
        # scheduler's lock (lock-order pass seed for the interrupt paths).
        # lock_order: GenerationEngine._lock -> GenerationEngine._retained_lock
        self._retained: dict[str, _Retained] = {}  # guarded_by: _retained_lock
        self._retained_slots: dict[int, str] = {}  # guarded_by: _retained_lock
        self._retained_lock = threading.Lock()
        # rids the PREEMPTION path requeued internally (client never saw a
        # response): losing their retained KV must convert them to a
        # client-visible interrupt, not a silent corruption. Engine-thread
        # only, like _warming.
        self._preempted_rids: set[str] = set()
        # next retained-KV TTL sweep (engine thread; 0 knob disables)
        self._next_reap = 0.0
        # KV shipping (prefill/decode disaggregation): inbound import
        # chunks stage on the CALLER's thread as host arrays keyed by rid
        # (like weight staging — decode dispatches never wait on the
        # transfer); only the final commit runs on the engine thread, where
        # it allocates a slot + blocks, scatters the rows into the pool,
        # and registers a pinned _Retained entry so the follow-up
        # /generate resumes through _try_resume with ZERO re-prefill.
        # _kv_staging_lock is a leaf lock like _retained_lock.
        # lock_order: GenerationEngine._lock -> GenerationEngine._kv_staging_lock
        self._kv_import_staging: dict[str, dict] = {}  # guarded_by: _kv_staging_lock
        self._kv_staging_lock = threading.Lock()
        self.kv_export_total = 0
        self.kv_export_tokens_total = 0
        self.kv_import_total = 0
        self.kv_import_tokens_total = 0
        self.kv_import_refused_version_total = 0
        self.kv_import_refused_capacity_total = 0
        self.kv_import_seconds_last = 0.0
        # Prompt-prefix KV reuse (the SGLang radix-cache role for the
        # dominant RL pattern): _slot_covered[i] = the token sequence (a
        # list, appended per decoded token) whose K/V rows live in cache
        # positions [0, len) of slot i. Rows stay
        # valid after a sequence finishes (until the slot is re-prefilled),
        # so a group's later samples clone the first sample's prompt rows
        # with one device-side copy and join batched decode directly —
        # n_samples-per-prompt rollouts prefill ONCE per group.
        self._slot_covered: list[list] = [[] for _ in range(b)]
        # weight version the slot's cached rows were computed under: clone
        # sources must match the CURRENT version (fresh requests always see
        # current-weight prefixes; in-flight/retained sequences keep their
        # accepted staleness but stop being clone sources after an update)
        self._slot_kv_version = np.zeros(b, np.int64)
        # radix-cache pins held on behalf of each slot's admission match
        # (released on finish/free so LRU eviction can reclaim the nodes)
        self._slot_pinned_nodes: list[list] = [[] for _ in range(b)]
        self.prefill_count = 0  # prompts prefilled (zero-re-prefill tests)
        self.prefill_dispatch_count = 0  # device dispatches (batching tests)
        # tokens actually run through prefill/extension dispatches — the
        # prefix-cache bench's headline denominator (clone/radix hits skip
        # these tokens entirely; prompt_tokens_total measures demand)
        self.prefill_tokens_computed_total = 0
        # chunked-prefill chunks dispatched (satellite observability;
        # chunked_prefill_count below counts COMPLETED warmups)
        self.prefill_chunks_total = 0
        # radix-cache admissions (cross-slot reuse) and their covered tokens
        self.radix_hit_count = 0
        self.prefix_clone_count = 0
        # cross-request partial prefix sharing (the general radix-reuse
        # case: different requests with a common system/few-shot prefix):
        # number of admissions served by copy-shared-rows + suffix-extend,
        # and how many prompt tokens skipped prefill that way
        self.prefix_extend_count = 0
        self.prefix_extend_saved_tokens = 0
        # intra-prompt chunked prefill (vLLM/SGLang-style): slots whose
        # long prompt is being written chunk-by-chunk between decode
        # iterations; invisible to decode until warm
        self._warming: dict[int, dict] = {}
        self.chunked_prefill_count = 0
        # token-boundary interruption ledger (tentpole observability):
        # total + by-reason ("manual" | "drain" | "preempt" | "chaos" |
        # "reaped"), resumes split by exact-match vs suffix-recompute, and
        # how many resumes crossed a staged weight commit (their per-token
        # versions span the commit — the version-mix telemetry shows it)
        self.interrupts_total = 0
        self.interrupts_by_reason: dict[str, int] = {}
        self.resumed_total = 0
        self.resumed_tokens_total = 0  # KV tokens reused (not recomputed)
        self.resume_suffix_recomputed_tokens_total = 0
        self.resumed_across_commit_total = 0
        self.preemptions_total = 0
        self.retained_kv_reaped_total = 0
        # served-token counters (the reference gserver_manager's per-server
        # token-usage tracking role, realhf/system/gserver_manager.py):
        # prompt_tokens_total counts every ADMITTED request's prompt
        # (prefill, prefix-clone, and abort-resume paths alike — it
        # measures demand, not prefill compute); generated counts sampled
        # tokens including each sequence's prefill-sampled first token
        self.prompt_tokens_total = 0
        self.generated_tokens_total = 0
        # decode dispatches issued (plain multi-step + speculative windows):
        # the overlap tests assert this keeps advancing while weight chunks
        # stream in, proving staging never fences the decode loop
        self.decode_dispatch_count = 0
        # weight-sync observability (surfaced via server /model_info): the
        # headline is weight_sync_stall_seconds — the fenced window on the
        # engine thread (commit dequeue -> version bump), which the
        # pipelined design shrinks to the final pointer flip
        self.weight_sync_stall_seconds_last = 0.0
        self.weight_sync_stall_seconds_total = 0.0
        self.weight_sync_commits_total = 0
        self.weight_sync_staged_chunks_total = 0
        self.weight_sync_staged_bytes_total = 0
        self.weight_sync_aborted_updates_total = 0
        # peer-to-peer propagation (server-side relay/peer-push hops,
        # incremented by GenerationServer; plain ints under the GIL):
        # chunks/bytes this server forwarded to relay children, forwards
        # that failed, last/total per-hop forward latency, and whole-model
        # pushes served to warming peers
        self.weight_relay_forwarded_chunks_total = 0
        self.weight_relay_forwarded_bytes_total = 0
        self.weight_relay_failed_forwards_total = 0
        self.weight_relay_hop_seconds_last = 0.0
        self.weight_relay_hop_seconds_total = 0.0
        self.weight_peer_pushes_total = 0
        # brackets every (params, version) co-publish so an exporter on
        # another thread (peer push) can never read a new tree under the
        # old version or vice versa; held only for pointer assignments.
        # Weight-plane acquisition order (checked by the lock-order pass):
        # chunk staging strictly before the publish pointer-swap — the
        # commit path drops _staging_lock before publishing, and nothing
        # may reach back into staging while holding the publish lock.
        # lock_order: _staging_lock -> _publish_lock
        self._publish_lock = threading.Lock()
        self._lock = threading.Lock()
        self._dead: Exception | None = None
        # distributed tracing (utils/tracing.py): request spans arrive
        # from the server as _Seq.span; engine internals stamp events
        # (admission wait, radix hit, prefill chunks, decode segments,
        # spec accepts, weight commits) onto them. None when disabled —
        # every hot-path site guards with `is not None` (pinned by a
        # code-inspection test), so tracing off allocates nothing.
        from areal_tpu.utils.tracing import Tracer

        self._tracer = Tracer.from_config(
            getattr(self.config, "tracing", None)
        )
        # unified metrics: TTFT + inter-token latency histograms (observed
        # once per request at finish — off the per-token path) and a
        # collector mirroring /model_info counters into gauges at scrape
        # time (so /metrics and /model_info agree by construction)
        from areal_tpu.utils import metrics as _metrics

        self._ttft_hist = _metrics.DEFAULT_REGISTRY.histogram(
            "areal_ttft_seconds", "time to first token per request"
        )
        self._itl_hist = _metrics.DEFAULT_REGISTRY.histogram(
            "areal_inter_token_seconds", "inter-token latency"
        )
        # TTFT decomposition: the single areal_ttft_seconds number split
        # into attributable phases — queue_wait (admission queue), prefill
        # (admission -> first token for freshly-prefilled requests),
        # kv_ship (import staging start -> commit on the DECODE server),
        # first_decode (admission -> first token for resumed/imported
        # sequences). Bounded label set; the disagg win and the KV-ship
        # cost each get their own series instead of one opaque number.
        self._ttft_phase_hist = _metrics.DEFAULT_REGISTRY.histogram(
            "areal_ttft_phase_seconds",
            "per-phase TTFT decomposition "
            "(queue_wait | prefill | kv_ship | first_decode)",
            labels=("phase",),
        )
        self._c_interrupts = _metrics.DEFAULT_REGISTRY.counter(
            "areal_interrupts_total",
            "token-boundary interruptions, by reason",
            labels=("reason",),
        )
        self._metrics_collector = None

        # one body; pixels=None (text) vs array (VLM) retraces by pytree
        # structure, so both paths share the cache-write/sampling code
        self._jit_prefill = jax.jit(
            functools.partial(self._prefill_impl),
            donate_argnums=(1,),
        )
        self._jit_decode = jax.jit(
            functools.partial(self._decode_impl),
            donate_argnums=(1,),
            static_argnames=("steps",),
        )
        self._jit_prefill_rot = jax.jit(
            self._prefill_rot_impl, donate_argnums=(1,)
        )
        self._jit_copy_block = jax.jit(
            self._copy_block_impl, donate_argnums=(0,)
        )
        self._jit_extend = jax.jit(self._extend_impl, donate_argnums=(1,))
        self._jit_spec_decode = jax.jit(
            self._spec_decode_impl, donate_argnums=(1,)
        )
        self._jit_import_blocks = jax.jit(
            self._import_blocks_impl, donate_argnums=(0,)
        )
        # qwen2_vl prefill retraces per (grid signature, bucket) — the image
        # grid is a static shape input like prefill buckets
        self._jit_cache_vlm: dict = {}

    @staticmethod
    def _copy_block_impl(cache, src_blk, dst_blk):
        """Copy ONE physical block (copy-on-write for a shared partial tail
        block): [L, BS, KH, D] moved pool-internally, no host roundtrip."""

        def cp(x):
            row = jax.lax.dynamic_index_in_dim(x, src_blk, 1, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(x, row, dst_blk, 1)

        # tree-wide: int8 pools carry ks/vs scale planes alongside k/v
        return jax.tree.map(cp, dict(cache))

    @staticmethod
    def _import_blocks_impl(cache, rows, ids):
        """Scatter shipped KV block rows into the pool: ``rows`` holds
        ``[L, n, BS, ...]`` per pool leaf and ``ids`` the ``n`` destination
        block ids. ``ids`` is padded to a power-of-two bucket with the
        trash block (the designated garbage sink — padded lanes write
        there), so the compile count stays logarithmic in ship size."""
        out = dict(cache)
        for k, r in rows.items():
            out[k] = cache[k].at[:, ids].set(r.astype(cache[k].dtype))
        return out

    # ------------------------------------------------------------------
    # Device steps
    # ------------------------------------------------------------------

    def _prefill_impl(
        self,
        params,
        cache,
        ids,  # [Tb] ragged packed stream — ANY mix of prompt lengths
        positions,  # [Tb] within-prompt positions
        segment_ids,  # [Tb] prompt index, pad = -1
        last_idx,  # [N] stream index of each prompt's final token
        token_blocks,  # [Tb] physical block per token (trash for pads)
        token_offsets,  # [Tb] row within each block
        rng,
        temp,  # [N]
        top_k,
        top_p,
        greedy,
        pixels=None,  # [Nimg, S, S, 3] (mini) / [P, pd] (qwen2_vl)
        positions3=None,  # [3, Tb] qwen2_vl M-RoPE positions
        image_grid_thw=None,  # static (jit-partial-bound) qwen2_vl grids
    ):
        if self._pp > 1:
            from areal_tpu.parallel.pipeline import prefill_stream_pp

            logits, cache = prefill_stream_pp(
                params, self.model_config, cache, ids, positions,
                segment_ids, last_idx, token_blocks, token_offsets,
                self.mesh, attn_spec=self.attn_spec, positions3=positions3,
                pixel_values=pixels, image_grid_thw=image_grid_thw,
            )
        else:
            logits, ks, vs = prefill_stream(
                params, self.model_config, ids, positions, segment_ids,
                last_idx, attn_spec=self.attn_spec, pixel_values=pixels,
                positions3=positions3, image_grid_thw=image_grid_thw,
            )
            # scatter the stream's K/V rows into the prompts' allocated
            # blocks; pad rows (stream tail, dummy rows) carry trash ids
            cache = write_prefill_blocks(
                cache, ks, vs, token_blocks, token_offsets
            )
        toks, logps = sample_tokens(logits, rng, temp, top_k, top_p, greedy)
        return toks, logps, cache

    def _extend_impl(self, params, cache, ids, start_len, table):
        """Suffix prefill for ONE sequence: run ``ids`` [1, Tq] against the
        ``start_len`` prefix rows reachable through ``table`` [1, NBT] and
        write the suffix K/V at positions [start_len, start_len+Tq).
        Logits are discarded — the caller leaves the final prompt token for
        the decode feed, same as the clone path.

        Tq is a padded bucket; pad tokens write garbage rows beyond the true
        suffix, which is safe: each such position is overwritten by its real
        token (one decode write per position) strictly before any query can
        attend it (decode masks kpos <= qpos and positions fill in order).
        The dispatch costs O(Tq · model), not O(B · Tq · model)."""
        _, cache = self._paged_decode(
            params, cache, ids,
            jnp.reshape(start_len, (1,)).astype(jnp.int32),
            table,
            jnp.ones((1,), bool),
            compute_logits=False,
        )
        return cache

    def _paged_decode(
        self, params, cache, ids, clen, table, active,
        compute_logits=True, pos_offset=None,
    ):
        """Single dispatch of paged decode, routed through the pipeline
        conveyor when the engine serves with pp > 1."""
        if self._pp > 1:
            from areal_tpu.parallel.pipeline import decode_step_paged_pp

            return decode_step_paged_pp(
                params, self.model_config, cache, ids, clen, table, active,
                self.mesh, attn_spec=self.attn_spec,
                compute_logits=compute_logits, pos_offset=pos_offset,
            )
        return decode_step_paged(
            params, self.model_config, cache, ids, clen, table, active,
            attn_spec=self.attn_spec, compute_logits=compute_logits,
            pos_offset=pos_offset,
        )

    def _decode_impl(
        self,
        params,
        cache,
        last_tokens,  # [B]
        cache_len,  # [B]
        block_table,  # [B, NBT] bucketed to the longest live sequence
        active,  # [B] bool
        rng,
        temp,
        top_k,
        top_p,
        greedy,
        pos_delta,  # [B] qwen2_vl M-RoPE decode offsets (zeros otherwise)
        steps: int,
    ):
        if (
            self._pp > 1
            and last_tokens.shape[0] % self._pp == 0
            and self.config.pp_rotate_decode
        ):
            # batch-group rotation: S stages busy every tick instead of one
            from areal_tpu.parallel.pipeline import decode_rotated_pp

            return decode_rotated_pp(
                params, self.model_config, cache, last_tokens, cache_len,
                block_table, active, self.mesh, rng, temp, top_k, top_p,
                greedy, steps, attn_spec=self.attn_spec,
                pos_offset=pos_delta,
            )

        def step(carry, step_rng):
            tokens, cache, clen = carry
            logits, cache = self._paged_decode(
                params, cache, tokens[:, None], clen,
                block_table, active, pos_offset=pos_delta,
            )
            nxt, logp = sample_tokens(
                logits[:, 0], step_rng, temp, top_k, top_p, greedy
            )
            nxt = jnp.where(active, nxt, tokens)
            clen = clen + active.astype(jnp.int32)
            return (nxt, cache, clen), (nxt, logp)

        rngs = jax.random.split(rng, steps)
        (_, cache, _), (toks, logps) = jax.lax.scan(
            step, (last_tokens, cache, cache_len), rngs
        )
        return toks, logps, cache  # [steps, B], [steps, B]

    def _spec_decode_impl(
        self,
        params,
        cache,
        last_tokens,  # [B] pending feed token per slot
        draft,  # [B, K] n-gram-proposed continuation tokens
        draft_len,  # [B] valid draft count (0 = plain decode for that slot)
        cache_len,  # [B]
        block_table,  # [B, NBT]
        active,  # [B] bool
        rng,
        temp,
        top_k,
        top_p,
        greedy,
        pos_delta,  # [B] M-RoPE decode offsets
    ):
        """One speculative window: verify K drafts per slot in a single
        K+1-token paged dispatch, then run the acceptance rule. Returns
        (tokens [B, K+1], logprobs [B, K+1], n_accepted [B], cache)."""
        logits, cache = spec_verify_step_paged(
            params, self.model_config, cache, last_tokens, draft,
            cache_len, block_table, active,
            attn_spec=self.attn_spec, pos_offset=pos_delta,
        )
        toks, logps, n_acc = spec_verify_tokens(
            logits, draft, draft_len, rng, temp, top_k, top_p, greedy
        )
        return toks, logps, n_acc, cache

    # ------------------------------------------------------------------
    # Host-side helpers
    # ------------------------------------------------------------------

    def _load_params_from(self, path: str):
        def putter(p, arr):
            shard = self._leaf_sharding(p)
            return jax.device_put(jnp.asarray(arr), shard)

        _, params = hf_io.load_hf_params(
            path, self.model_config, dtype=self.config.dtype, to_device=putter
        )
        return params  # every leaf already placed on its NamedSharding

    def _leaf_sharding(self, path):
        node = self._shardings
        for k in path:
            node = node[getattr(k, "key", k)]
        return node

    def _next_rng(self):
        self._rng_counter += 1
        return jax.random.fold_in(self._rng_base, self._rng_counter)

    def _bucket(self, n: int) -> int:
        """Static prompt-length bucket: powers of two up to prefill_chunk,
        then multiples of prefill_chunk (bounds compile count)."""
        chunk = self.config.prefill_chunk
        b = 64
        while b < min(n, chunk):
            b *= 2
        if n <= b:
            return min(b, self._max_bucket())
        return min(-(-n // chunk) * chunk, self._max_bucket())

    def _max_bucket(self) -> int:
        return self.config.max_seq_len

    def _stream_bucket(self, n: int) -> int:
        """Static bucket for the ragged prefill stream's TOTAL length —
        same ladder as _bucket but uncapped (a stream packs many prompts,
        so it may exceed max_seq_len)."""
        chunk = self.config.prefill_chunk
        b = 64
        while b < min(n, chunk):
            b *= 2
        if n <= b:
            return b
        return -(-n // chunk) * chunk

    # ------------------------------------------------------------------
    # KV block management (host side)
    # ------------------------------------------------------------------

    def _free_slot_blocks(self, i: int):
        """Release slot ``i``'s block references and clear its cached-prefix
        state. Never call on an active slot."""
        n = int(self._slot_nblocks[i])
        if n:
            self.pool.decref(self.block_table[i, :n])
        self._unpin_slot_nodes(i)
        self.block_table[i, :] = -1
        self._slot_nblocks[i] = 0
        self._slot_covered[i] = []
        self.cache_len[i] = 0
        self._slot_kv_version[i] = 0

    def _unpin_slot_nodes(self, i: int):
        """Release the radix-cache pins taken when slot ``i`` admitted via
        a cache match (idempotent: the list clears on first release)."""
        if self.prefix_cache is not None and self._slot_pinned_nodes[i]:
            self.prefix_cache.unpin(self._slot_pinned_nodes[i])
        self._slot_pinned_nodes[i] = []

    def _reclaim_blocks(self) -> bool:
        """Free one inactive slot's cached blocks (LRU). Plain
        finished-slot prefix caches go first; retained abort-resume state
        is evicted only when nothing else is left (its loss forces a full
        re-prefill on resume)."""
        with self._retained_lock:
            retained_slots = set(self._retained_slots)
            has_retained = bool(self._retained)
        cands = [
            i
            for i, s in enumerate(self.slots)
            if s is None
            and i not in retained_slots
            and i not in self._warming  # mid-warm blocks are LIVE
            and self._slot_nblocks[i] > 0
        ]
        if cands:
            self._free_slot_blocks(
                min(cands, key=lambda j: self._slot_last_use[j])
            )
            return True
        if has_retained:
            self._evict_lru_retained()  # demotes its slot to plain-cached
            return self._reclaim_blocks()
        return False

    def _alloc_blocks(self, n: int) -> list[int]:
        """Allocate ``n`` blocks, evicting cached prefixes as needed.

        Eviction ladder: inactive slot tables first (their full blocks are
        usually ALSO registered in the radix cache, so freeing the table
        keeps the prefix matchable while releasing the duplicate
        reference), then LRU unpinned radix nodes. Raises OutOfBlocks when
        live sequences hold everything."""
        if n <= 0:
            return []
        while True:
            try:
                return self.pool.alloc(n)
            except OutOfBlocks:
                if self._reclaim_blocks():
                    continue
                if self.prefix_cache is not None and self.prefix_cache.evict(
                    n - self.pool.n_free
                ):
                    continue
                raise

    def _on_weights_changed(self):
        """Version-fence the radix cache after ANY weight commit (staged
        pointer flip, disk/device refresh, LoRA merge): cached blocks are
        tagged with the version that computed them, match() only returns
        current-version nodes, and unpinned stale nodes are evicted NOW —
        a stale-version block can never be spliced into a new-version
        prefill. Runs on the engine thread."""
        if self.prefix_cache is not None:
            freed = self.prefix_cache.on_weights_changed(self.version)
            if freed:
                logger.info(
                    "weight commit v%d fenced the prefix cache: %d stale "
                    "block(s) evicted (%d still pinned by in-flight "
                    "sequences)",
                    self.version, freed, self.prefix_cache.n_cached_blocks,
                )

    def _stamp_active_spans(self, event: str, **attrs) -> None:
        """Append a trace event to every in-flight request's span (engine
        thread only). A weight commit that lands mid-generation is the
        canonical case: per-token versions already record the crossing,
        this makes it visible on the rollout's timeline too."""
        for s in self.slots:
            if s is not None and s.span is not None:
                s.span.event(event, **attrs)

    @property
    def eos_token_id(self) -> int | None:
        if self.tokenizer is not None:
            return getattr(self.tokenizer, "eos_token_id", None)
        return None

    # ------------------------------------------------------------------
    # Public API (thread-safe)
    # ------------------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="generation-engine", daemon=True
        )
        self._thread.start()
        if self._metrics_collector is None:
            from areal_tpu.utils import metrics as _metrics

            self._metrics_collector = (
                _metrics.DEFAULT_REGISTRY.register_collector(
                    self._collect_metrics
                )
            )

    def stop(self):
        self._shutdown.set()
        self._wake.set()
        if self._metrics_collector is not None:
            from areal_tpu.utils import metrics as _metrics

            _metrics.DEFAULT_REGISTRY.unregister_collector(
                self._metrics_collector
            )
            self._metrics_collector = None
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._tracer is not None:
            self._tracer.close()

    def submit(
        self,
        rid: str,
        input_ids: list[int],
        gconfig: GenerationHyperparameters,
        on_done: Callable[[ModelResponse], None],
        image_data: list | None = None,
        priority: int = 0,
        span=None,
        prefill_only: bool = False,
    ):
        """Enqueue a request; ``on_done(ModelResponse)`` fires from the engine
        thread when it finishes (stop/length/abort). ``priority`` orders
        admission (higher first; FIFO within a class). ``span`` (tracing
        on only) receives engine-internal events for this request.
        ``prefill_only`` marks a disaggregated-serving prefill leg: the
        finished sequence's KV is always retained AND pinned (regardless
        of ``kv_retain_seconds``) so :meth:`export_kv` can ship it."""
        if self._dead is not None:
            raise RuntimeError("generation engine loop died") from self._dead
        if gconfig.frequency_penalty:
            # refuse rather than silently sample without it: the JAX
            # sampler implements temperature/top_k/top_p/greedy only
            raise ValueError(
                "frequency_penalty is not implemented by the JAX sampling "
                "backend; set GenerationHyperparameters.frequency_penalty=0"
            )
        if len(input_ids) >= self.config.max_seq_len:
            resp = ModelResponse(
                input_tokens=list(input_ids), stop_reason="length"
            )
            on_done(resp)
            return
        if not self.scheduler.would_ever_fit(len(input_ids)):
            # admission control: a prompt beyond the token budget could
            # never admit — refuse NOW instead of parking it at the queue
            # head forever (the response mirrors the over-max_seq_len case)
            self.scheduler.refused_total += 1
            logger.warning(
                "refusing rid=%s: prompt of %d tokens exceeds the admission "
                "token budget %d (knob: JaxGenConfig.admission_token_budget)",
                rid, len(input_ids), self.scheduler.token_budget,
            )
            # admission decisions feed the flight recorder: a refusal
            # storm right before a wedge/crash is exactly the kind of
            # context the postmortem dump exists to capture
            from areal_tpu.utils import flight_recorder

            flight_recorder.record(
                "admission",
                "refused",
                rid=rid,
                prompt_tokens=len(input_ids),
                budget=self.scheduler.token_budget,
            )
            on_done(
                ModelResponse(input_tokens=list(input_ids), stop_reason="length")
            )
            return
        images = None
        grids = None
        if image_data:
            if not self.model_config.is_vlm:
                raise ValueError("model has no vision encoder but got images")
            got = sum(
                1 for t in input_ids if t == self.model_config.image_token_id
            )
            if self.model_config.is_qwen_vl:
                # HF-processor payloads: {"pixel_values": [P_i, pd],
                # "grid_thw": [t, h, w]} per image
                images, grids = [], []
                pd = None
                for item in image_data:
                    if not isinstance(item, dict) or "grid_thw" not in item:
                        raise ValueError(
                            "qwen2_vl images need {'pixel_values', "
                            "'grid_thw'} payloads"
                        )
                    arr = np.asarray(item["pixel_values"], np.float32)
                    grid = tuple(int(v) for v in item["grid_thw"])
                    from areal_tpu.models.vlm_qwen2 import patch_dim

                    pd = patch_dim(self.model_config)
                    t, h, w = grid
                    if arr.ndim != 2 or arr.shape != (t * h * w, pd):
                        raise ValueError(
                            f"pixel_values shape {arr.shape} != "
                            f"({t * h * w}, {pd}) for grid {grid}"
                        )
                    images.append(arr)
                    grids.append(grid)
                merge2 = self.model_config.vision_spatial_merge**2
                expected = sum(t * h * w // merge2 for t, h, w in grids)
            else:
                from areal_tpu.utils.image import decode_image

                images = [
                    decode_image(x) if isinstance(x, str) else np.asarray(x)
                    for x in image_data
                ]
                size = self.model_config.vision_image_size
                for img in images:
                    if tuple(img.shape) != (size, size, 3):
                        # validate HERE (caller thread): a malformed image
                        # must not detonate inside the shared engine loop
                        raise ValueError(
                            f"image shape {tuple(img.shape)} != "
                            f"({size}, {size}, 3)"
                        )
                expected = len(images) * self.model_config.vision_patches
            if got != expected:
                raise ValueError(
                    f"prompt carries {got} image placeholder tokens but "
                    f"the supplied images need {expected}"
                )
        seq = _Seq(
            rid=rid, prompt=list(input_ids), gconfig=gconfig, on_done=on_done,
            images=images, grids=grids, priority=priority, span=span,
            prefill_only=prefill_only,
        )
        self.scheduler.submit(seq, priority=priority)
        self._wake.set()

    def abort(self, rid: str):
        with self._lock:
            self._abort_rids.add(rid)
        self._wake.set()

    def interrupt(self, rid: str, reason: str = "manual"):
        """Stop ``rid`` at the next token boundary (the engine loop checks
        between decode chunks, so the wait is bounded by one
        ``decode_steps_per_call`` window, never by max generation length).
        The sequence answers with ``stop_reason="interrupt"`` carrying its
        partial output, and its KV stays retained (pinned, tagged with the
        current weight version) so a re-issue of prompt+accumulated resumes
        with zero re-prefill — or, after a staged commit, recomputes only
        the uncovered suffix and continues on the NEW weights."""
        with self._lock:
            self._interrupt_rids[rid] = reason
        self._wake.set()

    def interrupt_all(self, reason: str = "drain") -> None:
        """Interrupt every in-flight, warming, and queued request at the
        next token boundary and block until all their responses fired
        (bounded-time drain: wall time is one decode chunk plus response
        fan-out, not max generation length). Thread-safe; raises on engine
        death like any blocking command."""
        self._run_command("interrupt_all", reason)

    @property
    def healthy(self) -> bool:
        return self._dead is None

    def is_ready(self) -> bool:
        """Readiness, as distinct from liveness (:attr:`healthy`): the model
        is loaded (construction materializes params and the KV pool) AND the
        engine loop thread is running. The server's ``GET /ready`` gate —
        which fleet scale-out and the health-prober rejoin path wait on —
        additionally checks the weight version; this is the engine half."""
        return self._thread is not None and self._dead is None

    def pause(self, timeout: float = 60.0):
        """Abort all in-flight requests and stop admitting new ones (weight
        update fence). Raises if the engine thread doesn't acknowledge —
        proceeding with a weight update while requests run would violate the
        fence."""
        done = threading.Event()
        self._paused.set()
        self._cmd_queue.put(("pause_ack", done))
        self._wake.set()
        if not done.wait(timeout=timeout) and self._dead is None:
            raise TimeoutError(
                f"engine thread did not acknowledge pause within {timeout}s "
                "(long compile in progress?)"
            )

    def resume(self):
        self._paused.clear()
        self._wake.set()

    def _run_command(self, name: str, *args):
        """Submit one command to the engine thread and block until it is
        handled; raise a descriptive error if the engine thread does not
        complete it within ``config.command_timeout_seconds`` (a hung or
        compile-bound engine loop must name the command it is sitting on,
        not surface an anonymous queue.Empty after an arbitrary wait)."""
        done: queue.Queue = queue.Queue()
        self._cmd_queue.put((name, *args, done))
        self._wake.set()
        timeout = self.config.command_timeout_seconds
        try:
            err = done.get(timeout=timeout)
        except queue.Empty:
            if self._dead is not None:
                raise RuntimeError(
                    f"engine loop died while command {name!r} was pending"
                ) from self._dead
            raise TimeoutError(
                f"engine thread did not complete command {name!r} within "
                f"{timeout}s (knob: JaxGenConfig.command_timeout_seconds; "
                f"{self._cmd_queue.qsize()} command(s) still queued — long "
                "compile in progress, or the engine thread was never "
                "started?)"
            ) from None
        if err is not None:
            raise err

    def update_weights_from_disk(self, path: str, version: int | None = None):
        """Swap params in place; must run on the engine thread between
        dispatches. Blocks until done."""
        self._run_command("update_weights", path, version)

    def stage_weight_chunk(self, named: dict, version: int | None = None):
        """Stage one chunk of dotted-path-named host arrays for a pending
        weight update WITHOUT touching the live params: each array is
        device_put onto its target leaf's sharding from the CALLER's thread,
        so decode dispatches on the engine thread proceed untouched while
        the transfer streams in. ``version`` tags the update this chunk
        belongs to; a chunk tagged differently than the staged set
        supersedes it (torn/abandoned stream — the old staging is dropped,
        the server keeps serving its current version). ``None`` joins the
        current staging regardless of tag."""
        with self._staging_lock:
            if (
                version is not None
                and self._staging_version is not None
                and version != self._staging_version
            ):
                logger.warning(
                    "abandoning %d staged weight leaves tagged v%s: a chunk "
                    "for v%d superseded them (torn stream?)",
                    len(self._staged_leaves), self._staging_version, version,
                )
                self._staged_leaves = {}
                self.weight_sync_aborted_updates_total += 1
            if version is not None:
                self._staging_version = version
        params = self.params  # one consistent tree snapshot
        placed: dict[str, Any] = {}
        nbytes = 0
        for name, arr in named.items():
            node = params
            parts = name.split(".")
            try:
                for p in parts[:-1]:
                    node = node[p]
                leaf = node[parts[-1]]
            except (KeyError, TypeError):
                self.abandon_staged_weights()
                raise ValueError(f"unknown param leaf {name!r}") from None
            if tuple(arr.shape) != tuple(leaf.shape):
                self.abandon_staged_weights()
                raise ValueError(
                    f"shape mismatch for {name}: {arr.shape} vs {leaf.shape}"
                )
            placed[name] = jax.device_put(
                arr.astype(leaf.dtype)
                if getattr(arr, "dtype", None) != leaf.dtype
                else arr,
                leaf.sharding,
            )
            nbytes += int(
                getattr(arr, "nbytes", arr.size * arr.dtype.itemsize)
            )
        with self._staging_lock:
            if version is not None and self._staging_version != version:
                # superseded while we were placing (a racing chunk from a
                # NEWER update re-tagged the staging set): drop this
                # chunk's arrays rather than splice stale-version leaves
                # into the newer update's commit
                logger.warning(
                    "dropping %d staged leaves tagged v%d: staging was "
                    "re-tagged v%s while they were being placed",
                    len(placed), version, self._staging_version,
                )
                return
            self._staged_leaves.update(placed)
            self.weight_sync_staged_chunks_total += 1
            self.weight_sync_staged_bytes_total += nbytes

    def commit_staged_weights(self, version: int):
        """Atomically flip the live params to include every staged leaf and
        bump the served version — the ONLY fenced step of a pipelined
        weight update (runs on the engine thread between dispatches).
        Raises if nothing is staged or the staged set is tagged for a
        different version."""
        self._run_command("commit_staged", version)

    def abandon_staged_weights(self):
        """Drop any staged-but-uncommitted weight chunks (failed stream).
        The live params and version are untouched; the server keeps serving
        the old weights and the client's rejoin probe re-syncs it later."""
        with self._staging_lock:
            if self._staged_leaves or self._staging_version is not None:
                self._staged_leaves = {}
                self._staging_version = None
                self.weight_sync_aborted_updates_total += 1

    def snapshot_params_for_export(self) -> tuple[int, Any]:
        """A (version, params-tree) pair that is guaranteed CONSISTENT:
        every commit path publishes both under ``_publish_lock``, so a
        commit racing this call can never pair the old tree with the new
        version (or vice versa) — the exported weights are exactly the
        weights that version served."""
        with self._publish_lock:
            return self.version, self.params

    def export_weight_chunks(self, chunk_mb: int = 64):
        """Yield the live params as dotted-path host-array chunks of
        <= ``chunk_mb`` MB — the peer-sourcing half of weight
        propagation: ``POST /push_weights_to_peer`` streams these to a
        stale peer's /update_weights_from_tensor, so fleet scale-out
        warms newcomers from an in-rotation server instead of billing
        the trainer. Returns ``(version, generator)``; the tree
        reference is captured once (:meth:`snapshot_params_for_export`),
        so a commit mid-export cannot produce a mixed tree."""
        from areal_tpu.utils.wire import walk_named_leaves

        version, params = self.snapshot_params_for_export()
        budget = max(1, int(chunk_mb)) * 1_000_000

        def chunks():
            cur: dict[str, Any] = {}
            size = 0
            for path, leaf in walk_named_leaves(params):
                arr = np.asarray(jax.device_get(leaf))
                if cur and size + arr.nbytes > budget:
                    yield cur
                    cur, size = {}, 0
                cur[path] = arr
                size += arr.nbytes
            if cur:
                yield cur

        return version, chunks()

    # ------------------------------------------------------------------
    # KV shipping (prefill/decode disaggregation)
    # ------------------------------------------------------------------

    def export_kv(self, rid: str, chunk_mb: int = 8):
        """Snapshot a retained sequence's KV blocks (a finished
        prefill-only request, or any interrupted/pinned rid) as versioned,
        digest-stamped chunks for ``POST /import_kv`` on a decode peer.

        Returns ``(meta, chunks)``: ``meta`` carries the rid, the weight
        version the KV was computed under, the full token list
        (covered + the pending feed token — exactly what the decode
        server's ``_try_resume`` will be re-issued), and pool geometry the
        receiver validates against; ``chunks`` yields
        ``(named_arrays, digest)`` pairs of <= ``chunk_mb`` MB, where
        ``named_arrays`` holds per-pool-leaf block rows ("k"/"v", plus
        "ks"/"vs" scale planes for int8 pools) ready for the
        `utils/wire.py` encode path and ``digest`` is
        :func:`wire.chunk_digest` over the raw arrays (the receiver
        recomputes it after decode — a torn or corrupted body refuses
        loudly instead of decoding garbage attention state).

        The block gather runs ON the engine thread (one bounded command —
        the pool's buffers are donated every dispatch, so no other thread
        may touch them); the host pulls and chunking happen on the
        caller's thread against the gathered copies."""
        from areal_tpu.utils.wire import chunk_digest

        out: dict = {}
        self._run_command("export_kv_snapshot", rid, out)
        tokens = out["tokens"]
        rows = out["rows"]  # leaf -> device array [L, nb, BS, ...]
        n_cov = len(tokens) - 1
        nb = int(next(iter(rows.values())).shape[1])
        per_block = sum(
            int(a.nbytes) // max(1, nb) for a in rows.values()
        )
        blocks_per_chunk = max(
            1, (max(1, int(chunk_mb)) * 1_000_000) // max(1, per_block)
        )
        self.kv_export_total += 1
        self.kv_export_tokens_total += n_cov
        meta = {
            "rid": rid,
            "version": out["version"],
            "tokens": tokens,
            "block_size": self.block_size,
            "kv_quant": self.config.kv_quant,
            "n_blocks": nb,
        }

        def chunks():
            for lo in range(0, nb, blocks_per_chunk):
                hi = min(nb, lo + blocks_per_chunk)
                named = {
                    k: np.asarray(jax.device_get(a[:, lo:hi]))
                    for k, a in rows.items()
                }
                yield named, chunk_digest(named)

        return meta, chunks()

    def stage_kv_chunk(
        self, rid: str, version: int, seq_idx: int, named: dict
    ) -> None:
        """Stage one decoded KV-ship chunk (host arrays) for ``rid`` —
        caller-thread work, like weight-chunk staging: the engine loop
        never waits on the transfer. Chunks tagged with a different
        version than the staged set supersede it (torn-stream hygiene).
        Fails fast with :class:`KVVersionMismatch` when the ship's version
        already cannot match this engine (the commit re-checks
        authoritatively on the engine thread)."""
        if version != self.version:
            self.kv_import_refused_version_total += 1
            raise KVVersionMismatch(
                f"KV for rid={rid} was computed under weight version "
                f"{version} but this engine serves v{self.version}"
            )
        now = time.monotonic()
        with self._kv_staging_lock:
            # drop abandoned ships (a sender that died mid-stream must not
            # pin host RAM until process exit)
            stale = [
                r
                for r, st in self._kv_import_staging.items()
                if now - st["t0"] > 120.0
            ]
            for r in stale:
                del self._kv_import_staging[r]
            st = self._kv_import_staging.get(rid)
            if st is None or st["version"] != version:
                st = {"version": version, "t0": now, "chunks": {}}
                self._kv_import_staging[rid] = st
            st["chunks"][seq_idx] = named

    def commit_kv_import(self, rid: str, version: int, tokens: list[int]):
        """Assemble the staged chunks for ``rid`` and splice them into the
        pool (engine-thread command): allocate a free slot + blocks,
        scatter the rows, and register a pinned retained entry so the
        follow-up ``/generate`` with ``tokens`` (prompt + first sampled
        token) admits through ``_try_resume`` with zero re-prefill.
        Raises :class:`KVVersionMismatch` (HTTP 412) when a weight commit
        landed since the prefill, :class:`KVNoCapacity` (HTTP 503) when no
        slot/blocks are available even after eviction."""
        with self._kv_staging_lock:
            st = self._kv_import_staging.pop(rid, None)
        if st is None or st["version"] != version or not st["chunks"]:
            raise KVNoCapacity(
                f"no staged KV chunks for rid={rid} at version {version} "
                "(stream torn or superseded)"
            )
        parts = [st["chunks"][i] for i in sorted(st["chunks"])]
        rows = {
            k: (
                parts[0][k]
                if len(parts) == 1
                else np.concatenate([p[k] for p in parts], axis=1)
            )
            for k in parts[0]
        }
        self._run_command(
            "import_kv", rid, version, list(tokens), rows, st["t0"]
        )

    def release_kv(self, rid: str) -> None:
        """Drop the retained entry for ``rid`` (the prefill server calls
        this once a ship landed on the decode peer — the pinned source
        copy has served its purpose; the TTL reaper covers senders that
        die before getting here). Thread-safe; no-op for unknown rids."""
        self._evict_retained(rid)

    def update_weights_from_named_arrays(
        self, named: dict, version: int | None = None
    ):
        """Apply one chunk of dotted-path-named host arrays (the
        /update_weights_from_tensor payload) into the live sharded params.
        ``version=None`` = partial chunk (more coming, don't bump).

        Staging (device placement) runs on the CALLER's thread so decode
        continues between chunks; only the final commit (``version`` set)
        fences the engine thread for the pointer flip."""
        self.stage_weight_chunk(named, version)
        if version is not None:
            self.commit_staged_weights(version)

    def update_lora_from_named_arrays(
        self, named: dict, scale: float, version: int | None = None
    ):
        """Adapter-only weight update (reference: SGLang adapter hot-swap,
        areal/engine/sglang_remote.py:82-106). ``named`` holds dotted-path
        adapter leaves (``layers.wq_a`` [L, in, r] / ``layers.wq_b``
        [L, r, out] pairs — models/lora.py layout); the engine retains the
        pristine base params on first use and serves ``W + scale * A@B`` on
        every adapted leaf. A LoRA sync therefore ships megabytes (rank-r
        factors) instead of the full parameter set, which is the main
        operational reason to train LoRA in async RL."""
        self._run_command("update_lora", named, scale, version)

    def update_weights_from_device_pull(
        self,
        address: str,
        uuid: int,
        leaves: list,  # [(dotted_path, shape, dtype_str), ...] one chunk
        version: int | None = None,
        final: bool = True,
    ):
        """Cross-process device-path weight chunk (the reference's NCCL
        broadcast role, fsdp_engine.py:359-401): pull the staged buffers
        from the trainer's transfer server straight into this process's
        device memory — no safetensors body, no host staging — then stage
        like any named chunk (decode keeps dispatching; only the final
        chunk's commit fences the engine). ``version`` tags every chunk so
        a torn stream is superseded by the next update; the commit happens
        only when ``final`` and a version are both set."""
        import jax.experimental.transfer  # noqa: F401 — fail early if absent

        from areal_tpu.utils import device_transfer

        dev = self.mesh.devices.flat[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        specs = {
            path: jax.ShapeDtypeStruct(
                tuple(shape), jnp.dtype(dtype), sharding=sharding
            )
            for path, shape, dtype in leaves
        }
        named = device_transfer.pull(address, uuid, specs)
        self.stage_weight_chunk(named, version)
        if final and version is not None:
            self.commit_staged_weights(version)

    def update_weights_from_arrays(self, params, version: int | None = None):
        """Colocated device-to-device weight refresh: re-place live jax
        arrays (e.g. the train engine's params) onto this engine's shardings
        — on a shared chip/slice this is an HBM-local copy, no disk or host
        roundtrip (the fast path the reference needs NCCL broadcast for,
        SURVEY §3.3)."""
        self._run_command("update_weights_arrays", params, version)

    def get_version(self) -> int:
        return self.version

    def set_version(self, v: int):
        self.version = v

    @property
    def n_running(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def n_pending_work(self) -> int:
        """Requests the engine still owes a response: running slots,
        warming (chunked-prefill) slots, and the admission queue. The
        drain path polls this to decide when a server is idle."""
        return self.n_running + len(self._warming) + len(self.scheduler)

    def _note_pallas_fallback(self, site: str, reason: str) -> None:
        """Structured one-shot note that a requested Pallas serving kernel
        (``site`` in {"decode", "prefill"}) is serving on the XLA path
        instead (``reason``, e.g. "tp_size"): warn ONCE per (site, reason),
        count always. The ledger is exported as
        ``pallas_fallback_total{site,reason}`` by :meth:`metrics_snapshot`,
        so /model_info and /metrics both show when the fleet is off the
        fast path. See docs/kernels.md for the supported-combination
        matrix."""
        key = (site, reason)
        first = key not in self.pallas_fallbacks
        self.pallas_fallbacks[key] = self.pallas_fallbacks.get(key, 0) + 1
        if first:
            logger.warning(
                "pallas %s kernel requested but unsupported here (%s): "
                "serving on the XLA path — counted as "
                "pallas_fallback_total{site=%s,reason=%s}",
                site, reason, site, reason,
            )

    def serving_stats(self) -> dict:
        """Serving-plane observability in one place: pool occupancy and
        byte footprint, radix prefix-cache hit/miss/eviction counters,
        chunked-prefill progress, and admission-queue depth/wait. The
        server's ``/model_info`` and the StatsLogger surface
        (:meth:`record_serving_stats`) both read from here."""
        pc = self.prefix_cache
        sched = self.scheduler
        # retained-KV hygiene gauges: live entries, their byte footprint
        # (per-block pool bytes x blocks referenced by retained slots), and
        # the TTL reaper's lifetime count — a client that vanishes
        # mid-interrupt-loop shows up here instead of leaking silently
        with self._retained_lock:
            retained_n = len(self._retained)
            retained_blocks = sum(
                int(self._slot_nblocks[e.slot])
                for e in self._retained.values()
            )
        total_blocks = max(1, self.pool.n_used + self.pool.n_free)
        per_block_bytes = (
            self._kv_pool_kv_bytes + self._kv_pool_scale_bytes
        ) / total_blocks
        return {
            # serving role ("" generalist): non-numeric on purpose — the
            # JSON surface carries it, the numeric metrics snapshot skips it
            "role": self.config.role,
            "retained_kv_slots": retained_n,
            "retained_kv_bytes": int(retained_blocks * per_block_bytes),
            "retained_kv_reaped_total": self.retained_kv_reaped_total,
            "interrupts_total": self.interrupts_total,
            "resumed_total": self.resumed_total,
            "resumed_tokens_total": self.resumed_tokens_total,
            "resume_suffix_recomputed_tokens_total": (
                self.resume_suffix_recomputed_tokens_total
            ),
            "resumed_across_commit_total": self.resumed_across_commit_total,
            "preemptions_total": self.preemptions_total,
            "kv_blocks_used": self.pool.n_used,
            "kv_blocks_used_peak": self.pool.peak_used,
            "kv_blocks_free": self.pool.n_free,
            "kv_block_size": self.pool.block_size,
            # KV-pool memory gauge: total persistent pool bytes split into
            # row storage (int8 halves this vs bf16) and the quantized
            # pools' f32 scale-plane overhead
            "kv_pool_dtype": self._kv_pool_dtype,
            "kv_pool_bytes": self._kv_pool_kv_bytes
            + self._kv_pool_scale_bytes,
            "kv_pool_kv_bytes": self._kv_pool_kv_bytes,
            "kv_pool_scale_bytes": self._kv_pool_scale_bytes,
            "prefix_cache_enabled": pc is not None,
            "prefix_cache_blocks": pc.n_cached_blocks if pc else 0,
            "prefix_cache_hit_tokens_total": pc.hit_tokens_total if pc else 0,
            "prefix_cache_miss_tokens_total": (
                pc.miss_tokens_total if pc else 0
            ),
            "prefix_cache_evicted_blocks_total": (
                pc.evicted_blocks_total if pc else 0
            ),
            "prefix_cache_hit_rate": (
                pc.hit_tokens_total
                / max(1, pc.hit_tokens_total + pc.miss_tokens_total)
                if pc
                else 0.0
            ),
            "radix_hit_count": self.radix_hit_count,
            "prefill_tokens_computed_total": (
                self.prefill_tokens_computed_total
            ),
            "prefill_chunks_total": self.prefill_chunks_total,
            "admission_queue_depth": sched.depth,
            "admission_token_budget": sched.token_budget,
            "admission_refused_total": sched.refused_total,
            "queue_wait_seconds_total": sched.queue_wait_seconds_total,
            "queue_wait_seconds_last": sched.queue_wait_seconds_last,
            # fleet-autoscaler load signals: p95s over the request
            # histograms, surfaced via /model_info so the controller's
            # signal poll reads them without parsing Prometheus buckets.
            # Under disaggregation the prefill pool scales on queue
            # wait/TTFT, the decode pool on ITL p95.
            "ttft_p95_seconds": self._ttft_hist.quantile(0.95),
            "itl_p95_seconds": self._itl_hist.quantile(0.95),
            "queue_wait_p95_seconds": sched.queue_wait_p95(),
            # TTFT decomposition (per-phase p95s from the labeled
            # histogram): queue_wait / prefill / kv_ship / first_decode —
            # attributes the disagg win (and the KV-ship cost) instead of
            # folding everything into one opaque TTFT number
            "ttft_queue_wait_p95_seconds": self._ttft_phase_hist.labels(
                phase="queue_wait"
            ).quantile(0.95),
            "ttft_prefill_p95_seconds": self._ttft_phase_hist.labels(
                phase="prefill"
            ).quantile(0.95),
            "ttft_kv_ship_p95_seconds": self._ttft_phase_hist.labels(
                phase="kv_ship"
            ).quantile(0.95),
            "ttft_first_decode_p95_seconds": self._ttft_phase_hist.labels(
                phase="first_decode"
            ).quantile(0.95),
            # KV-shipping ledger (prefill/decode disaggregation)
            "kv_export_total": self.kv_export_total,
            "kv_export_tokens_total": self.kv_export_tokens_total,
            "kv_import_total": self.kv_import_total,
            "kv_import_tokens_total": self.kv_import_tokens_total,
            "kv_import_refused_version_total": (
                self.kv_import_refused_version_total
            ),
            "kv_import_refused_capacity_total": (
                self.kv_import_refused_capacity_total
            ),
            "kv_import_seconds_last": self.kv_import_seconds_last,
        }

    def record_serving_stats(self) -> None:
        """Push the serving-plane counters into the process-wide stats
        tracker, so training loops that commit StatsLogger rows (rehearsal
        runs included) record cache hit rates alongside throughput."""
        from areal_tpu.utils import stats_tracker

        stats = {
            f"serving/{k}": float(v)
            for k, v in self.serving_stats().items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        stats_tracker.DEFAULT_TRACKER.scalar(**stats)

    def metrics_snapshot(self, serving_stats: dict | None = None) -> dict:
        """Every numeric counter ``/model_info`` serves, flat — the ONE
        source both the JSON endpoint and the Prometheus collector read,
        so a ``/metrics`` scrape always agrees with ``/model_info``.

        ``serving_stats`` lets a caller that also needs the native-typed
        dict (``/model_info``) supply one read instead of taking the
        scheduler lock twice at two different instants."""
        out = {
            "weight_version": self.get_version(),
            "n_running": self.n_running,
            "prompt_tokens_total": self.prompt_tokens_total,
            "generated_tokens_total": self.generated_tokens_total,
            "prefill_count": self.prefill_count,
            "prefill_dispatch_count": self.prefill_dispatch_count,
            "prefix_clone_count": self.prefix_clone_count,
            "prefix_extend_count": self.prefix_extend_count,
            "prefix_extend_saved_tokens": self.prefix_extend_saved_tokens,
            "spec_steps_total": self.spec_steps_total,
            "spec_proposed_tokens_total": self.spec_proposed_tokens_total,
            "spec_accepted_tokens_total": self.spec_accepted_tokens_total,
            "spec_acceptance_rate": self.spec_acceptance_rate,
            # adaptive draft length: current mean per-slot draft window and
            # acceptance EWMA over the running batch (static config value /
            # 1.0 when idle or adaptation is off)
            "spec_draft_len_current": self._spec_draft_len_current(),
            "spec_accept_ewma": self._spec_accept_ewma_mean(),
            "weight_sync_stall_seconds": self.weight_sync_stall_seconds_last,
            "weight_sync_stall_seconds_total": (
                self.weight_sync_stall_seconds_total
            ),
            "weight_sync_commits_total": self.weight_sync_commits_total,
            "weight_sync_staged_chunks_total": (
                self.weight_sync_staged_chunks_total
            ),
            "weight_sync_staged_bytes_total": (
                self.weight_sync_staged_bytes_total
            ),
            "weight_sync_aborted_updates_total": (
                self.weight_sync_aborted_updates_total
            ),
            "weight_relay_forwarded_chunks_total": (
                self.weight_relay_forwarded_chunks_total
            ),
            "weight_relay_forwarded_bytes_total": (
                self.weight_relay_forwarded_bytes_total
            ),
            "weight_relay_failed_forwards_total": (
                self.weight_relay_failed_forwards_total
            ),
            "weight_relay_hop_seconds_last": (
                self.weight_relay_hop_seconds_last
            ),
            "weight_relay_hop_seconds_total": (
                self.weight_relay_hop_seconds_total
            ),
            "weight_peer_pushes_total": self.weight_peer_pushes_total,
            "decode_dispatch_count": self.decode_dispatch_count,
            # Pallas serving-kernel fallback ledger (_note_pallas_fallback):
            # total plus one labeled entry per (site, reason), so a scrape
            # shows not just THAT the fleet is off the fast path but where
            "pallas_fallback_total": sum(self.pallas_fallbacks.values()),
        }
        for (site, reason), n in sorted(self.pallas_fallbacks.items()):
            out[f"pallas_fallback_total{{site={site},reason={reason}}}"] = n
        # interruption ledger, labeled by reason (interrupts_total itself
        # arrives via serving_stats below, alongside the retained-KV gauges)
        for reason, n in sorted(self.interrupts_by_reason.items()):
            out[f"interrupts_total{{reason={reason}}}"] = n
        if serving_stats is None:
            serving_stats = self.serving_stats()
        for k, v in serving_stats.items():
            if isinstance(v, bool):
                out[k] = int(v)
            elif isinstance(v, (int, float)):
                out[k] = v
        return out

    def _collect_metrics(self, registry) -> None:
        """Registry collector (runs at scrape/export time only): mirror
        the live engine counters into ``areal_serving_*`` gauges."""
        g = registry.gauge(
            "areal_serving",
            "generation-engine serving counters (mirrors /model_info)",
            labels=("key",),
        )
        for k, v in self.metrics_snapshot().items():
            g.labels(key=k).set(float(v))

    @property
    def spec_acceptance_rate(self) -> float:
        """Lifetime accepted/proposed draft-token ratio (0.0 before any
        speculative window ran) — the ONE home for the headline spec-decode
        metric; the server and bench both read it from here."""
        if not self.spec_proposed_tokens_total:
            return 0.0
        return (
            self.spec_accepted_tokens_total / self.spec_proposed_tokens_total
        )

    # ------------------------------------------------------------------
    # Engine loop
    # ------------------------------------------------------------------

    def _loop(self):
        try:
            while not self._shutdown.is_set():
                self._drain_commands()
                if self._paused.is_set():
                    self._abort_all("abort")
                    self._wake.wait(timeout=0.1)
                    self._wake.clear()
                    continue
                self._handle_aborts()
                self._handle_interrupts()
                self._reap_retained()
                self._admit()
                if self.n_running == 0:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self._decode_chunk()
        except Exception as e:
            logger.exception("generation engine loop died")
            self._dead = e
            self._abort_all("abort")
            raise

    def _drain_commands(self):
        while True:
            try:
                cmd = self._cmd_queue.get_nowait()
            except queue.Empty:
                return
            if cmd[0] == "pause_ack":
                self._abort_all("abort")
                cmd[1].set()
            elif cmd[0] == "interrupt_all":
                _, reason, done = cmd
                try:
                    self._interrupt_everything(reason)
                    done.put(None)
                except Exception as e:
                    logger.exception("interrupt_all failed")
                    done.put(e)
            elif cmd[0] == "export_kv_snapshot":
                _, rid, out, done = cmd
                try:
                    out.update(self._snapshot_kv_for_export(rid))
                    done.put(None)
                except Exception as e:
                    # expected refusals (unknown rid) surface to the caller
                    # without a stack trace — the server maps them to HTTP
                    done.put(e)
            elif cmd[0] == "import_kv":
                _, rid, version, tokens, rows, t0, done = cmd
                try:
                    self._import_kv_commit(rid, version, tokens, rows, t0)
                    done.put(None)
                except Exception as e:
                    if not isinstance(
                        e, (KVVersionMismatch, KVNoCapacity)
                    ):
                        logger.exception("KV import failed")
                    done.put(e)
            elif cmd[0] == "commit_staged":
                _, version, done = cmd
                t0 = time.monotonic()
                try:
                    # the ONLY fenced step of a pipelined update: splice the
                    # staged leaves into a fresh tree (structure copy; leaves
                    # shared until replaced) and flip the pointer — decode
                    # between chunks never sees layer i at v(n+1) while
                    # layer j is still v(n), and a failed stream leaves the
                    # live params untouched
                    with self._staging_lock:
                        # validate WITHOUT consuming: a stale commit
                        # command (e.g. left queued after a _run_command
                        # timeout) must not destroy a NEWER update's
                        # staged set, and a commit that fails below (a
                        # deferred device error surfacing in the readiness
                        # check) must leave the full set in place — the
                        # client's retry of the final chunk then commits
                        # the WHOLE update, never just that chunk
                        staged_version = self._staging_version
                        if not self._staged_leaves:
                            raise RuntimeError(
                                f"commit of weight version {version} found "
                                "no staged chunks (stream torn or already "
                                "superseded); serving stays at "
                                f"v{self.version}"
                            )
                        if (
                            staged_version is not None
                            and staged_version != version
                        ):
                            raise RuntimeError(
                                "staged weight chunks are tagged "
                                f"v{staged_version} but commit asked for "
                                f"v{version}; leaving them for their own "
                                f"commit — serving stays at v{self.version}"
                            )
                        staged = dict(self._staged_leaves)
                    new_params = jax.tree.map(lambda x: x, self.params)
                    for name, arr in staged.items():
                        node = new_params
                        parts = name.split(".")
                        for p in parts[:-1]:
                            node = node[p]
                        node[parts[-1]] = arr
                    # staged leaves were device_put as they streamed in, so
                    # this readiness check is usually a no-op — the fence
                    # really is just the pointer flip
                    jax.block_until_ready(list(staged.values()))
                    # success: consume exactly what was committed (a chunk
                    # from a superseding update that raced in keeps its
                    # own staging)
                    with self._staging_lock:
                        if self._staging_version == staged_version:
                            for name in staged:
                                self._staged_leaves.pop(name, None)
                            if not self._staged_leaves:
                                self._staging_version = None
                    with self._publish_lock:
                        self.params = new_params
                        self.version = version
                    self._lora_base = None  # base changed; re-snapshot
                    self._on_weights_changed()
                    stall = time.monotonic() - t0
                    self.weight_sync_stall_seconds_last = stall
                    self.weight_sync_stall_seconds_total += stall
                    self.weight_sync_commits_total += 1
                    self._stamp_active_spans("weight_commit", version=version)
                    # chaos: an interrupt landing exactly between the
                    # pointer flip and the next decode chunk — the retained
                    # KV predates the commit while the resume decodes on
                    # the new version (the adversarial mixed-version case)
                    self._chaos_interrupt("mid-commit")
                    from areal_tpu.utils import flight_recorder

                    flight_recorder.record(
                        "commits",
                        "staged_commit",
                        version=version,
                        leaves=len(staged),
                        stall_seconds=stall,
                        n_running=self.n_running,
                    )
                    logger.info(
                        "weights updated (staged commit of %d leaves) -> "
                        "v%d (fenced %.4fs)",
                        len(staged), self.version, stall,
                    )
                    done.put(None)
                except Exception as e:
                    logger.exception("staged weight commit failed")
                    done.put(e)
            elif cmd[0] == "update_lora":
                _, named, scale, version, done = cmd
                try:
                    t0 = time.monotonic()
                    if self._lora_base is None:
                        # first adapter update: current params become the
                        # retained base (leaves shared, not copied — merges
                        # REPLACE leaves, never mutate them)
                        self._lora_base = jax.tree.map(lambda x: x, self.params)
                    base_layers = self._lora_base["layers"]
                    new_layers = dict(base_layers)
                    leaves = sorted(
                        n.split(".")[1][:-2]
                        for n in named
                        if n.startswith("layers.") and n.endswith("_a")
                    )
                    if not leaves:
                        raise ValueError(
                            f"no adapter leaf pairs in payload: {sorted(named)}"
                        )
                    for leaf in leaves:
                        a = jnp.asarray(named[f"layers.{leaf}_a"], jnp.float32)
                        b = jnp.asarray(named[f"layers.{leaf}_b"], jnp.float32)
                        w = base_layers[leaf]
                        if a.shape[1] != w.shape[1] or b.shape[2] != w.shape[2]:
                            raise ValueError(
                                f"adapter/base shape mismatch on {leaf}: "
                                f"{a.shape}x{b.shape} vs {w.shape}"
                            )
                        delta = jnp.einsum("lir,lro->lio", a, b) * scale
                        merged = (w.astype(jnp.float32) + delta).astype(w.dtype)
                        new_layers[leaf] = jax.device_put(merged, w.sharding)
                    new_params = dict(self._lora_base)
                    new_params["layers"] = new_layers
                    jax.block_until_ready(
                        [new_layers[leaf] for leaf in leaves]
                    )
                    with self._publish_lock:
                        self.params = new_params
                        if version is not None:
                            self.version = version
                        else:
                            self.version += 1
                    self._on_weights_changed()
                    self._stamp_active_spans(
                        "weight_commit", version=self.version
                    )
                    logger.info(
                        "weights updated (lora adapters %s) -> v%d in %.2fs",
                        ",".join(leaves), self.version, time.monotonic() - t0,
                    )
                    done.put(None)
                except Exception as e:
                    logger.exception("lora weight update failed")
                    done.put(e)
            elif cmd[0] in ("update_weights", "update_weights_arrays"):
                _, src, version, done = cmd
                try:
                    t0 = time.monotonic()
                    # a full refresh supersedes any staged-but-uncommitted
                    # stream: drop it so a torn update's device-placed
                    # leaves stop pinning memory the moment the server is
                    # re-synced (e.g. the quarantine-rejoin disk re-push)
                    self.abandon_staged_weights()
                    # a full-weight refresh changes the base: a later
                    # adapter-only update must re-snapshot
                    self._lora_base = None
                    if cmd[0] == "update_weights":
                        new = self._load_params_from(src)
                    else:
                        # force a copy: astype/device_put are no-ops for
                        # matching dtype+sharding, and aliasing the train
                        # engine's buffers is fatal once its next step
                        # donates them
                        new = jax.device_put(
                            jax.tree.map(
                                lambda x: jnp.array(
                                    x, dtype=self.dtype, copy=True
                                ),
                                src,
                            ),
                            self._shardings,
                        )
                    jax.block_until_ready(jax.tree_util.tree_leaves(new)[0])
                    # slow work (load/copy/readiness) stays OUTSIDE the
                    # publish lock; only the pointer+version flip is inside
                    with self._publish_lock:
                        self.params = new
                        self.version = (
                            version if version is not None
                            else self.version + 1
                        )
                    self._on_weights_changed()
                    self._stamp_active_spans(
                        "weight_commit", version=self.version
                    )
                    from areal_tpu.utils import flight_recorder

                    flight_recorder.record(
                        "commits",
                        "full_refresh",
                        version=self.version,
                        source="disk" if cmd[0] == "update_weights"
                        else "device",
                        n_running=self.n_running,
                    )
                    logger.info(
                        "weights updated (%s) -> v%d in %.2fs",
                        "disk" if cmd[0] == "update_weights" else "device",
                        self.version,
                        time.monotonic() - t0,
                    )
                    done.put(None)
                except Exception as e:  # surface to caller
                    logger.exception("weight update failed")
                    done.put(e)

    def _abort_all(self, reason: str):
        retain = reason == "abort" and self.config.retain_kv_on_abort
        for i, seq in enumerate(self.slots):
            if seq is not None:
                self._finish(i, reason, retain=retain)
        # mid-warm slots answer too (their partially-written KV is
        # discarded — it may span a weight update and must not survive)
        for slot in list(self._warming):
            seq = self._warming.pop(slot)["seq"]
            self._free_slot_blocks(slot)
            seq.on_done(self._response(seq, reason))
        # flush queued-but-not-admitted requests too: client re-issues them
        for seq in self.scheduler.drain():
            self._preempted_rids.discard(seq.rid)
            seq.on_done(self._response(seq, reason))

    def _handle_aborts(self):
        with self._lock:
            rids, self._abort_rids = self._abort_rids, set()
        if not rids:
            return
        for i, seq in enumerate(self.slots):
            if seq is not None and seq.rid in rids:
                self._finish(i, "abort")
                rids.discard(seq.rid)
        for slot in list(self._warming):
            seq = self._warming[slot]["seq"]
            if seq.rid in rids:
                del self._warming[slot]
                self._free_slot_blocks(slot)
                seq.on_done(self._response(seq, "abort"))
                rids.discard(seq.rid)
        if rids:
            # the rid may still be waiting in the admission queue — filter
            # it out there too (otherwise the abort is silently lost and
            # the request is admitted later)
            for seq in self.scheduler.remove_rids(rids):
                if seq.rid in self._preempted_rids:
                    # an aborted preempted-victim's pinned KV would linger
                    # until the TTL reaper; its client just cancelled, so
                    # drop the pin now
                    self._preempted_rids.discard(seq.rid)
                    self._evict_retained(seq.rid)
                seq.on_done(self._response(seq, "abort"))

    # ------------------------------------------------------------------
    # Token-boundary interruption (engine thread)
    # ------------------------------------------------------------------

    def _handle_interrupts(self):
        """Serve pending interrupt() requests between decode chunks (the
        token boundary): running slots finish with stop_reason="interrupt"
        and retained+pinned KV; warming slots cancel their chunked prefill
        (partial KV discarded — it may span a weight update); queued rids
        answer with zero tokens. All bounded by one loop iteration."""
        with self._lock:
            if not self._interrupt_rids:
                return
            reasons, self._interrupt_rids = self._interrupt_rids, {}
        for i, seq in enumerate(self.slots):
            if seq is not None and seq.rid in reasons:
                self._interrupt_slot(i, reasons.pop(seq.rid))
        for slot in list(self._warming):
            seq = self._warming[slot]["seq"]
            if seq.rid in reasons:
                self._interrupt_warming(slot, reasons.pop(seq.rid))
        if reasons:
            for seq in self.scheduler.remove_rids(set(reasons)):
                self._note_interrupt(seq, reasons.get(seq.rid, "manual"))
                seq.on_done(self._response(seq, "interrupt"))

    def _interrupt_everything(self, reason: str):
        """The drain primitive behind :meth:`interrupt_all`: every running,
        warming, and queued request answers "interrupt" NOW. Unlike
        :meth:`_abort_all` the running slots' responses carry
        stop_reason="interrupt" and their KV is pinned, so a peer (or this
        server, pre-restart) resumes them token-exactly."""
        retain = self.config.retain_kv_on_abort
        for i, seq in enumerate(self.slots):
            if seq is not None:
                self._interrupt_slot(i, reason, retain=retain)
        for slot in list(self._warming):
            self._interrupt_warming(slot, reason)
        for seq in self.scheduler.drain():
            self._preempted_rids.discard(seq.rid)
            self._note_interrupt(seq, reason)
            seq.on_done(self._response(seq, "interrupt"))

    def _interrupt_slot(self, i: int, reason: str, retain: bool | None = None):
        """Finish running slot ``i`` with stop_reason="interrupt",
        retaining its KV pinned under the rid (version-tagged for the
        resume path's commit-crossing accounting)."""
        seq = self.slots[i]
        if seq is None:
            return
        self._note_interrupt(seq, reason)
        if retain is None:
            retain = self.config.retain_kv_on_abort
        self._finish(i, "interrupt", retain=retain, pin=True)

    def _interrupt_warming(self, slot: int, reason: str):
        """Cancel a mid-chunked-prefill slot: its partially-written KV is
        discarded (it may straddle a weight commit and must not survive);
        the client re-issues the prompt and admits fresh."""
        seq = self._warming.pop(slot)["seq"]
        self._free_slot_blocks(slot)
        self._note_interrupt(seq, reason)
        seq.on_done(self._response(seq, "interrupt"))

    def _note_interrupt(self, seq: _Seq, reason: str):
        self.interrupts_total += 1
        self.interrupts_by_reason[reason] = (
            self.interrupts_by_reason.get(reason, 0) + 1
        )
        self._c_interrupts.labels(reason=reason).inc()
        if seq.span is not None:
            seq.span.event(
                "interrupt", reason=reason, tokens=len(seq.out_tokens)
            )

    def _retain_seq(self, slot: int, seq: _Seq, pin: bool):
        """Record slot ``slot``'s cache as resumable KV for ``seq.rid``.
        Generalizes the k-tokens-emitted math to k=0 (a slot interrupted
        right after chunked-warm completion, before its first decode):
        the cache then covers prompt[:-1] and prompt[-1] is the pending
        feed token."""
        if seq.out_tokens:
            # cache covers prompt + all outputs but the last sampled token
            # (whose K/V is written when it is fed to the next decode step)
            covered = tuple(seq.prompt) + tuple(seq.out_tokens[:-1])
            feed = int(seq.out_tokens[-1])
        elif len(seq.prompt) >= 2:
            covered = tuple(seq.prompt[:-1])
            feed = int(seq.prompt[-1])
        else:
            return  # single-token prompt, nothing warmed: not resumable
        with self._retained_lock:
            stale = self._retained.pop(seq.rid, None)
            if stale is not None:
                self._retained_slots.pop(stale.slot, None)
            self._retained[seq.rid] = _Retained(
                slot=slot,
                covered=covered,
                feed_tok=feed,
                ts=time.monotonic(),
                version=self.version,
                pinned=pin,
            )
            self._retained_slots[slot] = seq.rid

    # ------------------------------------------------------------------
    # KV shipping internals (engine thread)
    # ------------------------------------------------------------------

    def _snapshot_kv_for_export(self, rid: str) -> dict:
        """Engine-thread half of :meth:`export_kv`: gather the retained
        slot's block rows into FRESH device arrays (one bounded take per
        pool leaf — safe to hand to another thread; unlike the live pool
        they are never donated)."""
        with self._retained_lock:
            ent = self._retained.get(rid)
        if ent is None:
            raise KeyError(
                f"no retained KV for rid={rid} (finished without "
                "prefill_only, already shipped, or TTL-reaped)"
            )
        n_cov = len(ent.covered)
        nb = self.pool.blocks_for_tokens(n_cov)
        if nb == 0 or int(self._slot_nblocks[ent.slot]) < nb:
            raise KeyError(
                f"retained KV for rid={rid} has no exportable blocks"
            )
        blocks = jnp.asarray(
            np.ascontiguousarray(self.block_table[ent.slot, :nb])
        )
        rows = {
            k: jnp.take(a, blocks, axis=1) for k, a in self.cache.items()
        }
        return {
            "version": ent.version,
            "tokens": list(ent.covered) + [int(ent.feed_tok)],
            "rows": rows,
        }

    def _import_kv_commit(
        self, rid: str, version: int, tokens: list[int], rows: dict,
        t0: float,
    ):
        """Engine-thread half of :meth:`commit_kv_import`. Version fence
        FIRST (authoritative — the staged-weight commit path bumps
        ``self.version`` on this same thread, so no TOCTOU), then slot +
        block allocation with the normal eviction ladder, then one
        bucketed scatter dispatch, then the pinned retained entry the
        resume path keys on."""
        if version != self.version:
            self.kv_import_refused_version_total += 1
            raise KVVersionMismatch(
                f"KV for rid={rid} was computed under weight version "
                f"{version} but this engine serves v{self.version} (a "
                "commit landed between prefill and import)"
            )
        n_cov = len(tokens) - 1
        if n_cov < 1:
            raise ValueError(
                f"KV import for rid={rid} needs >= 2 tokens "
                f"(covered + feed), got {len(tokens)}"
            )
        if set(rows) != set(self.cache):
            raise ValueError(
                f"KV import leaves {sorted(rows)} do not match this "
                f"pool's {sorted(self.cache)} (kv_quant mismatch between "
                "prefill and decode pools?)"
            )
        nb_need = self.pool.blocks_for_tokens(n_cov)
        for k, r in rows.items():
            want = self.cache[k].shape
            if (
                r.shape[0] != want[0]
                or r.shape[1] != nb_need
                or tuple(r.shape[2:]) != tuple(want[2:])
            ):
                raise ValueError(
                    f"KV import leaf {k!r} shape {tuple(r.shape)} does "
                    f"not fit pool {tuple(want)} ({nb_need} blocks of "
                    f"{self.block_size} tokens expected — block_size "
                    "mismatch between pools?)"
                )
        with self._retained_lock:
            retained_slots = set(self._retained_slots)
        free = [
            i
            for i, s in enumerate(self.slots)
            if s is None
            and i not in retained_slots
            and i not in self._warming
        ]
        if not free:
            self._evict_lru_retained()
            with self._retained_lock:
                retained_slots = set(self._retained_slots)
            free = [
                i
                for i, s in enumerate(self.slots)
                if s is None
                and i not in retained_slots
                and i not in self._warming
            ]
        if not free:
            self.kv_import_refused_capacity_total += 1
            raise KVNoCapacity(
                f"KV import for rid={rid}: every slot is running, "
                "warming, or pinned"
            )
        slot = free[0]
        self._free_slot_blocks(slot)
        try:
            blocks = self._alloc_blocks(nb_need)
        except OutOfBlocks:
            self.kv_import_refused_capacity_total += 1
            raise KVNoCapacity(
                f"KV import for rid={rid} needs {nb_need} blocks; live "
                "sequences hold the pool"
            ) from None
        bucket = 1
        while bucket < nb_need:
            bucket *= 2
        ids = np.full(bucket, TRASH_BLOCK, np.int32)
        ids[:nb_need] = blocks
        padded = {}
        for k, r in rows.items():
            if bucket != nb_need:
                pad = np.zeros(
                    (r.shape[0], bucket - nb_need) + tuple(r.shape[2:]),
                    r.dtype,
                )
                r = np.concatenate([r, pad], axis=1)
            padded[k] = jnp.asarray(r)
        self.cache = self._jit_import_blocks(
            self.cache, padded, jnp.asarray(ids)
        )
        self.block_table[slot, :nb_need] = blocks
        self.block_table[slot, nb_need:] = -1
        self._slot_nblocks[slot] = nb_need
        self.cache_len[slot] = n_cov
        self._slot_covered[slot] = [int(t) for t in tokens[:-1]]
        self._slot_kv_version[slot] = version
        self.pos_delta[slot] = 0
        self.last_token[slot] = int(tokens[-1])
        now = time.monotonic()
        self._slot_last_use[slot] = now
        with self._retained_lock:
            stale = self._retained.pop(rid, None)
            if stale is not None:
                self._retained_slots.pop(stale.slot, None)
            self._retained[rid] = _Retained(
                slot=slot,
                covered=tuple(int(t) for t in tokens[:-1]),
                feed_tok=int(tokens[-1]),
                ts=now,
                version=version,
                pinned=True,
            )
            self._retained_slots[slot] = rid
        dur = now - t0
        self.kv_import_total += 1
        self.kv_import_tokens_total += n_cov
        self.kv_import_seconds_last = dur
        self._ttft_phase_hist.labels(phase="kv_ship").observe(dur)
        from areal_tpu.utils import flight_recorder

        flight_recorder.record(
            "kv_ship",
            "import",
            rid=rid,
            tokens=n_cov,
            blocks=nb_need,
            version=version,
            seconds=round(dur, 6),
        )
        logger.info(
            "imported shipped KV for rid=%s: %d tokens into %d blocks "
            "(slot %d, v%d, %.3fs since staging began) — next /generate "
            "resumes with zero re-prefill",
            rid, n_cov, nb_need, slot, version, dur,
        )

    def _reap_retained(self):
        """TTL reaper for retained-KV entries (hygiene satellite): a client
        that disconnects mid-interrupt-loop must not pin KV until LRU
        pressure. Runs on the engine loop at ~1s cadence; a reaped entry
        that belonged to an internally-requeued preemption victim converts
        to a client-visible interrupt (partial output; the client's resume
        loop re-issues token-exactly)."""
        ttl = self.config.retained_kv_ttl_seconds
        if ttl <= 0:
            return
        now = time.monotonic()
        if now < self._next_reap:
            return
        self._next_reap = now + max(0.05, min(ttl / 4.0, 1.0))
        cutoff = now - ttl
        with self._retained_lock:
            expired = [
                rid for rid, e in self._retained.items() if e.ts <= cutoff
            ]
            for rid in expired:
                ent = self._retained.pop(rid)
                self._retained_slots.pop(ent.slot, None)
        for rid in expired:
            self.retained_kv_reaped_total += 1
            logger.info(
                "retained KV for rid=%s reaped after %.1fs TTL "
                "(knob: JaxGenConfig.retained_kv_ttl_seconds)", rid, ttl,
            )
            if rid in self._preempted_rids:
                self._preempted_rids.discard(rid)
                for seq in self.scheduler.remove_rids({rid}):
                    self._note_interrupt(seq, "reaped")
                    seq.on_done(self._response(seq, "interrupt"))

    def _chaos_interrupt(self, site: str, slot: int | None = None):
        """Seeded chaos hook (AREAL_CHAOS_INTERRUPT): fire an interrupt at
        an adversarial point. ``slot`` targets a specific running/warming
        slot (mid-chunked-prefill, radix-warm); None interrupts the first
        running slot (mid-commit). Off = one env lookup."""
        from areal_tpu.utils import chaos

        if not chaos.interrupt_point(site):
            return
        if slot is not None:
            if self.slots[slot] is not None:
                self._interrupt_slot(slot, "chaos")
            elif slot in self._warming:
                self._interrupt_warming(slot, "chaos")
            return
        for i, seq in enumerate(self.slots):
            if seq is not None:
                self._interrupt_slot(i, "chaos")
                return

    # ------------------------------------------------------------------
    # Priority preemption (engine thread, driven from _admit)
    # ------------------------------------------------------------------

    def _maybe_preempt_for(self, seq: _Seq) -> bool:
        """When ``seq`` (already popped by _admit) cannot be admitted, try
        interrupting the lowest-priority running victim with priority
        STRICTLY below ``seq.priority``: its KV is retained pinned and it
        requeues at its original position (no client-visible response).
        Returns True when a victim was preempted — the caller retries the
        admission pass."""
        if not self.config.enable_preemption:
            return False
        running = [
            (i, s) for i, s in enumerate(self.slots) if s is not None
        ]
        victim = self.scheduler.preemption_victim(running, seq.priority)
        if victim is None:
            return False
        self._preempt_slot(victim)
        return True

    def _preempt_slot(self, slot: int):
        """Interrupt running slot ``slot`` WITHOUT responding to its
        client: KV retained pinned, the sequence (with its accumulated
        tokens/logprobs/versions) pushed back at its original queue
        position; _try_resume re-admits it with zero re-prefill once
        capacity returns. If pool pressure later evicts the pinned entry,
        the eviction path converts it to a client-visible interrupt."""
        seq = self.slots[slot]
        if seq is None:
            return
        self.slots[slot] = None
        self.preemptions_total += 1
        self._note_interrupt(seq, "preempt")
        self._retain_seq(slot, seq, pin=True)
        self._cache_insert_slot(slot)
        self._unpin_slot_nodes(slot)
        self._slot_last_use[slot] = time.monotonic()
        self._preempted_rids.add(seq.rid)
        if seq.sched_entry is not None:
            self.scheduler.push_front(seq.sched_entry)
        else:  # defensive: never admitted through _admit (colocated use)
            self.scheduler.submit(seq, priority=seq.priority)
        logger.info(
            "preempted rid=%s (slot %d, priority %d) for a higher-priority "
            "admission; %d token(s) retained",
            seq.rid, slot, seq.priority, len(seq.out_tokens),
        )

    def _extend_chunk(self, slot: int, ids_chunk, start: int):
        """One bucketed suffix-extension dispatch writing slot's prompt
        tokens [start, start+len) — shared by prefix extension and
        intra-prompt chunked prefill. Chunk length buckets and the table
        width pads to a power of two: arbitrary shapes would recompile the
        model-sized extend program per distinct length; surplus -1 table
        entries gather the trash block and are masked by position."""
        bucket = self._bucket(len(ids_chunk))
        self.prefill_tokens_computed_total += len(ids_chunk)
        self.prefill_chunks_total += 1
        ids = np.zeros((1, bucket), np.int32)
        ids[0, : len(ids_chunk)] = ids_chunk
        nbt = 1
        while nbt < self._slot_nblocks[slot]:
            nbt *= 2
        nbt = min(nbt, self.max_blocks_per_seq)
        self.cache = self._jit_extend(
            self.params, self.cache, jnp.asarray(ids), jnp.int32(start),
            jnp.asarray(self.block_table[slot, :nbt][None]),
        )

    def _advance_warming(self, token_budget: int) -> int:
        """Write the next chunk(s) of each warming slot's long prompt
        (intra-prompt chunked prefill: decode proceeds between chunks, so
        one 32k admission cannot stall running requests for its whole
        prompt). Returns the remaining token budget."""
        chunk_sz = self.config.chunked_prefill_tokens
        for slot in list(self._warming):
            st = self._warming[slot]
            seq = st["seq"]
            limit = len(seq.prompt) - 1  # last token feeds the first decode
            while token_budget > 0 and st["off"] < limit:
                n = min(chunk_sz, limit - st["off"], token_budget)
                self._extend_chunk(
                    slot, seq.prompt[st["off"]: st["off"] + n], st["off"]
                )
                st["off"] += n
                token_budget -= n
                if seq.span is not None:
                    seq.span.event(
                        "prefill_chunk", tokens=n, offset=st["off"]
                    )
                self._chaos_interrupt("mid-chunked-prefill", slot=slot)
                if slot not in self._warming:
                    break  # chaos cancelled this warm mid-prompt
            if slot not in self._warming:
                continue
            if st["off"] >= limit:
                del self._warming[slot]
                self.chunked_prefill_count += 1
                self.prompt_tokens_total += len(seq.prompt)
                seq.slot = slot
                self.slots[slot] = seq
                self.cache_len[slot] = limit
                self.last_token[slot] = seq.prompt[-1]
                self.pos_delta[slot] = 0
                self._slot_covered[slot] = list(seq.prompt[:-1])
                # a weight update that landed MID-warm leaves mixed-version
                # KV: poison it as a clone source (-1, like image slots)
                self._slot_kv_version[slot] = (
                    st["version"] if st["version"] == self.version else -1
                )
                self._slot_last_use[slot] = time.monotonic()
                self._cache_insert_slot(slot)
            if token_budget <= 0:
                break
        return token_budget

    def _admit(self):
        """Fill slots from the input queue: resume retained requests with
        zero re-prefill, otherwise prefill into a free slot. Prefill work per
        loop iteration is budgeted in TOKENS (scheduler-level chunked
        prefill): a burst of long-prompt admissions cannot stall in-flight
        decode for more than ~one budget's worth of prefill compute, while
        short prompts still batch-ramp quickly."""
        if (
            self.prefix_cache is not None
            and self.prefix_cache.version != self.version
        ):
            # version moved outside the command handlers (set_version from
            # a reconcile path): fence lazily before any match can run
            self._on_weights_changed()
        chunk_sz = self.config.chunked_prefill_tokens
        if self.n_running == 0:
            token_budget = 1 << 62
        elif chunk_sz > 0:
            # chunked prefill on: the per-iteration budget is ~a couple of
            # chunks, so decode dispatches between every budget's worth of
            # warming — a long admission interleaves instead of stalling
            # the running batch for its whole prompt
            token_budget = max(chunk_sz * 2, self.config.prefill_chunk)
        else:
            token_budget = max(self.config.prefill_chunk * 4, 512)
        token_budget = self._advance_warming(token_budget)
        pending: list[_Seq] = []  # prompts awaiting one packed prefill
        pending_slots: list[int] = []
        pending_blocks: list[list[int]] = []
        pending_tokens = [0]

        def flush():
            if pending:
                landed = list(pending_slots)
                self._prefill_seqs(
                    list(pending), list(pending_slots), list(pending_blocks)
                )
                pending.clear()
                pending_slots.clear()
                pending_blocks.clear()
                pending_tokens[0] = 0
                # the flushed requests left pending_held and became live
                # slot tables: fold them into the incremental held set
                for s in landed:
                    note_admitted(s)

        # distinct active/warming blocks, computed at most once per pass
        # and updated incrementally as admissions land (a per-pop rebuild
        # is O(batch x blocks_per_seq) of host work on the hot loop)
        live_blocks: set | None = None

        def note_admitted(slot: int):
            if live_blocks is not None:
                nb = int(self._slot_nblocks[slot])
                live_blocks.update(
                    int(x) for x in self.block_table[slot, :nb]
                )
                live_blocks.discard(-1)

        def stamp_admitted(s: _Seq, ent: dict, resumed: bool = False):
            # TTFT decomposition, phase 1: time spent queued before this
            # admission landed (from ORIGINAL submission — a requeued
            # entry keeps t_first). ``resumed`` steers phase 2's label:
            # admission->first-token is decode-only for a zero-re-prefill
            # resume but prefill compute for a fresh placement.
            now = time.monotonic()
            s.t_admitted = now
            s.admitted_via_resume = resumed
            self._ttft_phase_hist.labels(phase="queue_wait").observe(
                max(0.0, now - ent["t_first"])
            )

        while token_budget > 0:
            popped = self.scheduler.pop()
            if popped is None:
                break
            seq, entry = popped
            # preemption hands the entry back via push_front so a victim
            # requeues at its ORIGINAL position
            seq.sched_entry = entry
            if seq.span is not None:
                # queue wait measured from ORIGINAL submission (a
                # requeued entry keeps t_first, like the scheduler stats)
                seq.span.event(
                    "admission",
                    queue_wait=round(
                        max(0.0, time.monotonic() - entry["t_first"]), 6
                    ),
                    queue_depth=self.scheduler.depth,
                )
            if self._try_resume(seq):
                stamp_admitted(seq, entry, resumed=True)
                note_admitted(seq.slot)
                continue  # resume costs no device dispatch
            if seq.out_tokens:
                # an internally-requeued preemption victim whose retained
                # KV was lost: a fresh prefill cannot re-create mid-sequence
                # state, so convert to a client-visible interrupt (the
                # client's resume loop replays prompt+accumulated)
                self._preempted_rids.discard(seq.rid)
                self._note_interrupt(seq, "evicted")
                seq.on_done(self._response(seq, "interrupt"))
                continue
            if live_blocks is None:
                live_blocks = self._live_block_set()
            pending_held = sum(len(b) for b in pending_blocks) * self.block_size
            radix_m = self._radix_match(seq)
            if not self._admission_ok(
                seq, extra_held=pending_held,
                covered=radix_m.covered if radix_m else 0,
                held_tokens=len(live_blocks) * self.block_size,
            ):
                if self._maybe_preempt_for(seq):
                    # a strictly-lower-priority victim released its blocks:
                    # requeue the popped request at the FRONT and retry the
                    # whole pass with a fresh held-set
                    self.scheduler.push_front(entry)
                    live_blocks = None
                    continue
                # token-budget admission control: the pool cannot hold this
                # request right now — keep it QUEUED (it retains its place)
                # instead of thrashing the prefix cache with evictions that
                # cannot add up to enough blocks anyway
                self.scheduler.push_front(entry)
                flush()
                return
            with self._retained_lock:
                retained_slots = set(self._retained_slots)
                has_retained = bool(self._retained)
            free = [
                i
                for i, s in enumerate(self.slots)
                if s is None
                and i not in retained_slots
                and i not in pending_slots
                and i not in self._warming
            ]
            if not free and has_retained:
                self._evict_lru_retained()
                with self._retained_lock:
                    retained_slots = set(self._retained_slots)
                free = [
                    i
                    for i, s in enumerate(self.slots)
                    if s is None
                    and i not in retained_slots
                    and i not in pending_slots
                    and i not in self._warming
                ]
            if not free:
                if self._maybe_preempt_for(seq):
                    self.scheduler.push_front(entry)
                    live_blocks = None
                    continue
                self.scheduler.push_front(entry)  # no capacity; retry later
                flush()
                return
            if (
                pending
                and self.config.enable_prefix_reuse
                and len(seq.prompt) >= 2
            ):
                # a prompt sharing a reusable prefix with a PENDING request
                # flushes the batch first, so its KV lands and this request
                # admits by block-sharing instead of re-prefilling: a full
                # twin (sampling group) costs ONE prefill + n-1 clones, and
                # a long shared system/few-shot prefix costs one prefill +
                # cheap suffix extensions
                prefix = np.asarray(seq.prompt[:-1])
                best = 0
                for p in pending:
                    m = min(len(p.prompt), prefix.size)
                    if m <= best:
                        continue
                    d = np.flatnonzero(
                        np.asarray(p.prompt[:m]) != prefix[:m]
                    )
                    best = max(best, int(d[0]) if d.size else m)
                if best >= min(
                    prefix.size, self.config.prefix_extend_min
                ) and best > 0:
                    flush()
            if self._try_clone(seq, free[0]):
                stamp_admitted(seq, entry, resumed=True)
                note_admitted(free[0])
                continue  # block sharing + at most one block copy
            radix_cost = self._try_radix(seq, free[0], match=radix_m)
            if radix_cost is not None:
                # radix-cache hit: only the uncovered suffix cost prefill
                # compute (0 for a full-cover hit)
                stamp_admitted(seq, entry, resumed=(radix_cost == 0))
                note_admitted(free[0])
                token_budget -= radix_cost
                continue
            # a fresh prefill owns its blocks exclusively: release the
            # slot's old cached prefix, then draw blocks for the prompt
            self._free_slot_blocks(free[0])
            try:
                blocks = self._alloc_blocks(
                    self.pool.blocks_for_tokens(len(seq.prompt))
                )
            except OutOfBlocks:
                self.scheduler.push_front(entry)  # pool full of live seqs
                flush()
                return
            if self.prefix_cache is not None and not seq.images:
                # charged only once the admission actually lands — an
                # OutOfBlocks requeue above must not deflate the hit rate
                # on every retry of the same request
                self.prefix_cache.miss_tokens_total += len(seq.prompt)
            chunk_sz = self.config.chunked_prefill_tokens
            if (
                chunk_sz > 0
                and not seq.images
                and len(seq.prompt) - 1 > chunk_sz
            ):
                # intra-prompt chunked prefill: this prompt warms chunk by
                # chunk across engine iterations (decode runs in between);
                # the slot stays invisible to decode until warm, and the
                # final prompt token feeds the first decode step (the
                # clone-resume recipe — no sampling inside prefill at all)
                slot = free[0]
                self.block_table[slot, : len(blocks)] = blocks
                self.block_table[slot, len(blocks):] = -1
                self._slot_nblocks[slot] = len(blocks)
                self._warming[slot] = {
                    "seq": seq, "blocks": blocks, "off": 0,
                    "version": self.version,
                }
                stamp_admitted(seq, entry)
                note_admitted(slot)
                token_budget = self._advance_warming(token_budget)
                continue
            # ragged packed prefill: mixed lengths and image prompts all
            # join the same stream; flush first when this prompt would
            # push the dispatch past the stream cap
            cap = max(
                self.config.prefill_chunk * self.config.prefill_batch,
                self._stream_bucket(len(seq.prompt)),
            )
            if pending and pending_tokens[0] + len(seq.prompt) > cap:
                flush()
            stamp_admitted(seq, entry)
            pending.append(seq)
            pending_slots.append(free[0])
            pending_blocks.append(blocks)
            pending_tokens[0] += len(seq.prompt)
            if len(pending) >= self.config.prefill_batch:
                flush()
            token_budget -= len(seq.prompt)
        flush()

    def _try_resume(self, seq: _Seq) -> bool:
        """Resume a retained continuation by rid.

        Exact match (re-issued tokens == retained cache contents + the
        pending feed token) re-admits with ZERO device dispatch — the
        abort-resume fast path. When the retained cache covers only a
        PREFIX of the re-issue, recompute just the uncovered suffix via
        one extension dispatch and continue decoding: this is the
        in-flight weight-swap path — after a staged commit the suffix
        (and all further decode) runs on the NEW version while the
        covered prefix keeps the version-tagged KV it was interrupted
        with, and the response's per-token ``versions`` span the commit
        (surfaced live by the version-mix telemetry)."""
        with self._retained_lock:
            ent = self._retained.get(seq.rid)
        if ent is None:
            return False
        slot = ent.slot
        full = tuple(seq.prompt) + tuple(seq.out_tokens)
        exact = full == ent.covered + (ent.feed_tok,)
        n_cov = len(ent.covered)
        middle: list[int] = []
        if not exact:
            if (
                len(full) <= n_cov
                or full[:n_cov] != ent.covered
                or seq.images  # M-RoPE positions: text-only extension path
            ):
                self._evict_retained(seq.rid)
                return False
            middle = list(full[n_cov:-1])
            # bucket guard BEFORE committing: the extension dispatch pads
            # to a power-of-two bucket; a resume too close to max_seq_len
            # falls back to the fresh-prefill path
            if middle and (
                n_cov + self._bucket(len(middle)) > self.config.max_seq_len
            ):
                self._evict_retained(seq.rid)
                return False
        # pop the entry (so the eviction ladder cannot reap it mid-resume)
        # and mark the slot live BEFORE drawing blocks — _alloc_blocks may
        # run the reclaim ladder, which must not free this slot's rows
        with self._retained_lock:
            self._retained.pop(seq.rid, None)
            self._retained_slots.pop(slot, None)
        seq.slot = slot
        self.slots[slot] = seq
        if middle:
            need = self.pool.blocks_for_tokens(len(full) - 1)
            have = int(self._slot_nblocks[slot])
            if need > have:
                try:
                    extra = self._alloc_blocks(need - have)
                except OutOfBlocks:
                    # continuation unservable right now: drop it and let
                    # the caller's normal admission path requeue/prefill
                    self.slots[slot] = None
                    seq.slot = -1
                    self._free_slot_blocks(slot)
                    return False
                self.block_table[slot, have:need] = extra
                self._slot_nblocks[slot] = need
            self._extend_chunk(slot, middle, start=n_cov)
            self.cache_len[slot] = len(full) - 1
            self._slot_covered[slot] = list(full[:-1])
            self.resume_suffix_recomputed_tokens_total += len(middle)
        else:
            # cache_len already holds len(covered); decode feeds full[-1]
            self._slot_covered[slot] = list(full[:-1])
        self.last_token[slot] = int(full[-1])
        self.prompt_tokens_total += len(seq.prompt)
        self.resumed_total += 1
        self.resumed_tokens_total += n_cov
        if ent.version != self.version:
            # the continuation crosses a weight commit: its KV rows mix
            # versions, so poison the slot as a clone/radix source; the
            # per-token versions the decode loop stamps from here on carry
            # the NEW version while the pre-interrupt tokens keep the old
            self.resumed_across_commit_total += 1
            self._slot_kv_version[slot] = -1
        if seq.span is not None:
            seq.span.event(
                "resume",
                exact=exact,
                covered=n_cov,
                recomputed=len(middle),
                kv_version=ent.version,
                version=self.version,
            )
        self._preempted_rids.discard(seq.rid)
        return True

    def _live_block_set(self) -> set:
        """Distinct physical blocks committed to ACTIVE work (running +
        warming slots) — shared prefix blocks count once. Retained
        abort-resume state and idle prefix caches are excluded: both are
        evictable on demand, so counting them would wedge admission with
        nothing running. Computed once per _admit pass and updated
        incrementally as admissions land (a per-pop rebuild is
        O(batch x blocks_per_seq) on the engine hot loop)."""
        live: set = set()
        for i, s in enumerate(self.slots):
            if s is not None or i in self._warming:
                nb = int(self._slot_nblocks[i])
                live.update(int(x) for x in self.block_table[i, :nb])
        live.discard(-1)
        return live

    def _held_tokens(self) -> int:
        return len(self._live_block_set()) * self.block_size

    def _radix_match(self, seq: _Seq):
        """The admission pass's ONE trie walk for this request (shared by
        the budget discount and _try_radix). None when the radix tier
        cannot apply."""
        if self.prefix_cache is None or seq.images or len(seq.prompt) < 2:
            return None
        return self.prefix_cache.match(seq.prompt[: len(seq.prompt) - 1])

    def _admission_ok(
        self,
        seq: _Seq,
        extra_held: int = 0,
        covered: int = 0,
        held_tokens: int | None = None,
    ) -> bool:
        """Token-budget + pool-headroom admission control: admit only when
        (a) the configured budget covers running + warming + this prompt,
        and (b) free + evictable blocks can actually hold the prompt —
        otherwise the eviction ladder would wipe every cached prefix and
        STILL fail, which is exactly the thrash this check exists to
        avoid. ``extra_held`` covers same-pass admissions still waiting in
        the pending prefill batch (blocks drawn, slot tables not yet
        written).

        A radix-covered prefix (``covered``, from the admission pass's one
        trie walk) is discounted from the request's demand: those blocks
        already exist in the pool, so a group sibling that will admit by
        reference must not be held back (head-of-line-blocking the queue)
        for capacity it cannot consume. The match may be evicted before
        the actual admission — then the fresh path simply fails
        allocation and requeues, same as before."""
        need_tokens = max(1, len(seq.prompt) - covered)
        held = (
            self._held_tokens() if held_tokens is None else held_tokens
        ) + extra_held
        if not self.scheduler.admit_ok(need_tokens, held):
            return False
        # exact headroom, no double counting: every usable block is either
        # held by an active/warming table (not evictable) or reclaimable —
        # free, inactive-slot-cached, retained (demotable), or
        # radix-cached (a block referenced by BOTH an inactive table and a
        # cache node still frees exactly once, which summing the two
        # populations would overstate). Pinned cache nodes belong to
        # active slots, so their blocks are already in the held set.
        need = self.pool.blocks_for_tokens(need_tokens)
        avail = (self.pool.num_blocks - 1) - held // self.block_size
        return need <= avail

    def _cache_insert_slot(self, i: int) -> None:
        """Register slot ``i``'s covered FULL blocks in the radix cache
        (no-op when the rows predate the current weights or encode
        pixels)."""
        if self.prefix_cache is None:
            return
        if self._slot_kv_version[i] != self.version:
            return
        cov = self._slot_covered[i]
        nfull = len(cov) // self.block_size
        if nfull == 0:
            return
        self.prefix_cache.insert(
            cov[: nfull * self.block_size], self.block_table[i, :nfull]
        )

    def _try_radix(self, seq: _Seq, dst: int, match=None) -> int | None:
        """Admission via the radix prefix cache: the longest cached
        full-block prefix of the prompt is REFERENCED into ``dst``'s block
        table (refcount sharing, no copy — full blocks are never appended
        into, so no copy-on-write is needed) and only the uncovered suffix
        runs prefill compute, through the suffix-extension dispatch or —
        for a long suffix — the chunked-prefill warming path. Returns the
        suffix token count charged against the admission budget, or None
        when the cache offers nothing useful. ``match`` is the admission
        pass's earlier trie walk; it is re-validated (eviction or a
        version fence may have struck between the walk and this call —
        e.g. _try_clone's allocations) and re-run only if dead."""
        if self.prefix_cache is None or seq.images:
            return None
        n = len(seq.prompt)
        if n < 2:
            return None
        m = match
        if m is None or any(
            node.parent is None or node.version != self.prefix_cache.version
            for node in m.nodes
        ):
            m = self.prefix_cache.match(seq.prompt[: n - 1])
        covered = m.covered
        if covered == 0:
            return None
        suffix = n - 1 - covered
        if suffix > 0 and covered < self.config.prefix_extend_min:
            return None  # too little sharing to beat a batched prefill
        chunk_sz = self.config.chunked_prefill_tokens
        warm = chunk_sz > 0 and suffix > chunk_sz
        if suffix > 0 and not warm and (
            covered + self._bucket(suffix) > self.config.max_seq_len
        ):
            return None  # padded suffix write would overrun the table
        # pin the matched path and take the sequence's OWN references
        # before any allocation below can trigger eviction
        self.pool.incref(m.blocks)
        self.prefix_cache.pin(m.nodes)
        self._free_slot_blocks(dst)
        if suffix == 0:
            extra = 0  # decode allocates growth blocks on demand
        elif warm:
            extra = self.pool.blocks_for_tokens(n) - len(m.blocks)
        else:
            extra = (
                self.pool.blocks_for_tokens(covered + self._bucket(suffix))
                - len(m.blocks)
            )
        try:
            fresh = self._alloc_blocks(max(extra, 0))
        except OutOfBlocks:
            self.pool.decref(m.blocks)
            self.prefix_cache.unpin(m.nodes)
            return None
        table = list(m.blocks) + fresh
        self.block_table[dst, : len(table)] = table
        self.block_table[dst, len(table):] = -1
        self._slot_nblocks[dst] = len(table)
        self._slot_pinned_nodes[dst] = list(m.nodes)
        self.prefix_cache.hit_tokens_total += covered
        self.prefix_cache.miss_tokens_total += suffix
        self.radix_hit_count += 1
        if seq.span is not None:
            seq.span.event(
                "radix_hit", covered_tokens=covered, suffix_tokens=suffix
            )
        now = time.monotonic()
        self._slot_last_use[dst] = now
        if warm:
            # uncovered suffix is long: warm it chunk-by-chunk between
            # decode iterations (slot invisible to decode until warm;
            # _advance_warming charges prompt_tokens_total at completion).
            # Admission itself dispatched NOTHING — the suffix is charged
            # against the iteration budget chunk-by-chunk as
            # _advance_warming actually writes it, so returning it here
            # too would double-bill and starve this iteration's peers.
            self._warming[dst] = {
                "seq": seq, "blocks": table, "off": covered,
                "version": self.version,
            }
            self._chaos_interrupt("radix-warm", slot=dst)
            return 0
        self.prompt_tokens_total += n
        if suffix > 0:
            self._extend_chunk(dst, seq.prompt[covered: n - 1], covered)
        seq.slot = dst
        self.slots[dst] = seq
        self.cache_len[dst] = n - 1
        self.last_token[dst] = seq.prompt[-1]
        self.pos_delta[dst] = 0  # cached prefixes are text-only
        self._slot_covered[dst] = list(seq.prompt[: n - 1])
        self._slot_kv_version[dst] = self.version
        self._cache_insert_slot(dst)  # register the fresh suffix blocks
        return suffix

    def _try_clone(self, seq: _Seq, dst: int) -> bool:
        """Prompt-prefix KV reuse, full and partial.

        Full: some slot already caches this exact prompt minus its final
        token — copy those rows into ``dst`` and skip prefill entirely; the
        request enters decode feeding the final prompt token, which produces
        the first-output-token logits exactly as a fresh prefill would. The
        group-sampling fast path (n_samples identical prompts -> one
        prefill + n-1 row copies).

        Partial (cross-request sharing, the SGLang-radix role the reference
        relies on): a different request whose prompt shares >=
        ``prefix_extend_min`` leading tokens (identical system/few-shot
        prefix) copies the shared rows and runs ONE suffix-extension
        dispatch (``_extend_impl``) over only the unshared tail — the
        shared 1k-token prefix prefills once for the whole batch."""
        if not self.config.enable_prefix_reuse or seq.images:
            return False
        n = len(seq.prompt)
        if n < 2:
            return False
        prefix = list(seq.prompt[: n - 1])
        prompt_arr = np.asarray(prefix)  # one conversion, sliced per slot
        src, best = None, 0
        for i, cov in enumerate(self._slot_covered):
            if self._slot_kv_version[i] != self.version:
                continue  # rows predate the current weights (or hold pixels)
            if cov[: n - 1] == prefix:  # full match
                src, best = i, n - 1
                if i == dst:  # in-place reuse of dst's own rows: no copy
                    break
            elif src is None or best < n - 1:
                # longest common prefix with this slot's covered tokens
                # (vectorized — a per-token Python loop over every slot
                # would stall the engine loop on long prompts)
                m = min(len(cov), n - 1)
                if m > best:
                    diff = np.flatnonzero(np.asarray(cov[:m]) != prompt_arr[:m])
                    sh = int(diff[0]) if diff.size else m
                    if sh > best:
                        src, best = i, sh
        if src is None or best == 0:
            return False
        if best < n - 1:
            if best < self.config.prefix_extend_min:
                return False  # too little sharing to beat a batched prefill
            # the padded suffix write must stay inside the per-sequence
            # block-table range
            if best + self._bucket(n - 1 - best) > self.config.max_seq_len:
                return False
        # Block-level sharing (vLLM/SGLang copy-on-write discipline): full
        # blocks of the shared prefix are REFERENCED, not copied; only the
        # partially-filled tail block — which this sequence will append
        # into — is copied. Pin every source block first so pool eviction
        # during allocation cannot free rows we are about to use.
        bs = self.block_size
        nfull = best // bs
        partial = best % bs
        src_ids = self.block_table[src, : nfull + (1 if partial else 0)].copy()
        # snapshot BEFORE any table mutation: the in-place branch (and a
        # reclaim triggered by _alloc_blocks) can zero src's version while
        # its rows are pinned and still perfectly current
        src_kv_version = self._slot_kv_version[src]
        self.pool.incref(src_ids)
        if dst != src:
            self._free_slot_blocks(dst)
        else:
            # in-place reuse: drop the old table (its full-prefix blocks are
            # the very src_ids we just pinned; surplus tail blocks free).
            # Clear the covered-tokens state too — a failed allocation below
            # must not leave covered tokens pointing at a dropped table.
            old_n = int(self._slot_nblocks[dst])
            self.pool.decref(self.block_table[dst, :old_n])
            self.block_table[dst, :] = -1
            self._slot_nblocks[dst] = 0
            self._slot_covered[dst] = []
            self.cache_len[dst] = 0
            self._slot_kv_version[dst] = 0
        if best == n - 1:
            extra = 0  # decode allocates growth blocks on demand
        else:
            bucket = self._bucket(n - 1 - best)
            extra = (
                self.pool.blocks_for_tokens(best + bucket)
                - nfull
                - (1 if partial else 0)
            )
        try:
            fresh = self._alloc_blocks((1 if partial else 0) + max(extra, 0))
        except OutOfBlocks:
            self.pool.decref(src_ids)
            return False
        new_table = list(src_ids[:nfull])
        if partial:
            # copy-on-write of the shared tail block
            tail = fresh.pop(0)
            self.cache = self._jit_copy_block(
                self.cache, jnp.int32(src_ids[nfull]), jnp.int32(tail)
            )
            self.pool.decref([src_ids[nfull]])  # pin released; we keep a copy
            new_table.append(tail)
        new_table.extend(fresh)
        self.block_table[dst, : len(new_table)] = new_table
        self.block_table[dst, len(new_table):] = -1
        self._slot_nblocks[dst] = len(new_table)
        self.prompt_tokens_total += len(seq.prompt)
        if best == n - 1:
            self.prefix_clone_count += 1
            self._slot_kv_version[dst] = src_kv_version
        else:
            # suffix extension over prompt[best : n-1] (bucket-padded; pad
            # rows are overwritten before they're ever attended — see
            # _extend_impl)
            self._extend_chunk(dst, seq.prompt[best: n - 1], best)
            self.prefix_extend_count += 1
            self.prefix_extend_saved_tokens += best
            self._slot_kv_version[dst] = self.version
        seq.slot = dst
        self.slots[dst] = seq
        self.cache_len[dst] = n - 1
        self.last_token[dst] = seq.prompt[-1]
        self.pos_delta[dst] = 0  # clone/extension sources are text-only
        self._slot_covered[dst] = list(prefix)
        self._slot_last_use[dst] = time.monotonic()
        if self.prefix_cache is not None:
            # slot-level reuse is still a prefix-cache hit from the
            # operator's perspective: the hit-rate metrics cover BOTH
            # reuse tiers
            self.prefix_cache.hit_tokens_total += best
            self.prefix_cache.miss_tokens_total += n - 1 - best
            self._cache_insert_slot(dst)
        return True

    def _prefill_rot_impl(
        self, params, cache, ids, positions, segment_ids, last_idx,
        token_blocks, token_offsets, rng, temp, top_k, top_p, greedy,
    ):
        """Jit body for the rotated pp prefill: S stacked streams in, one
        sampled token per (stream, row) out."""
        from areal_tpu.parallel.pipeline import prefill_rotated_pp

        logits, cache = prefill_rotated_pp(
            params, self.model_config, cache, ids, positions, segment_ids,
            last_idx, token_blocks, token_offsets, self.mesh,
            attn_spec=self.attn_spec,
        )
        s, n, v = logits.shape
        toks, logps = sample_tokens(
            logits.reshape(s * n, v), rng,
            temp.reshape(-1), top_k.reshape(-1), top_p.reshape(-1),
            greedy.reshape(-1),
        )
        return toks.reshape(s, n), logps.reshape(s, n), cache

    def _prefill_seqs_rotated(
        self, seqs: list[_Seq], slots: list[int], blocks: list[list[int]]
    ):
        """Split an admission burst into S packed streams (balanced
        longest-first) and prefill them through the rotated wavefront."""
        self.prefill_count += len(seqs)
        self.prefill_dispatch_count += 1
        self.prompt_tokens_total += sum(len(s.prompt) for s in seqs)
        self.prefill_tokens_computed_total += sum(len(s.prompt) for s in seqs)
        for s in seqs:
            if s.span is not None:
                s.span.event(
                    "prefill_dispatch",
                    prompt_tokens=len(s.prompt),
                    packed=len(seqs),
                )
        s_pp = self._pp
        bs = self.block_size
        order = sorted(
            range(len(seqs)), key=lambda i: -len(seqs[i].prompt)
        )
        stream_of = {}
        loads = [0] * s_pp
        members: list[list[int]] = [[] for _ in range(s_pp)]
        for i in order:
            si = loads.index(min(loads))
            loads[si] += len(seqs[i].prompt)
            stream_of[i] = (si, len(members[si]))
            members[si].append(i)
        tb = self._stream_bucket(max(loads))
        # pinned row count = prefill_batch (the admission cap, so any
        # member skew fits): a varying n_rows would retrace the jit per
        # distinct burst shape; dummy rows only widen last_idx/sampling
        n_rows = self.config.prefill_batch
        ids = np.zeros((s_pp, tb), np.int32)
        positions = np.zeros((s_pp, tb), np.int32)
        segment_ids = np.full((s_pp, tb), -1, np.int32)
        last_idx = np.full((s_pp, n_rows), tb - 1, np.int32)
        temp = np.ones((s_pp, n_rows), np.float32)
        top_k = np.zeros((s_pp, n_rows), np.int32)
        top_p = np.ones((s_pp, n_rows), np.float32)
        greedy = np.zeros((s_pp, n_rows), bool)
        token_blocks = np.full((s_pp, tb), TRASH_BLOCK, np.int32)
        token_offsets = np.zeros((s_pp, tb), np.int32)
        for si, mem in enumerate(members):
            cursor = 0
            for ri, i in enumerate(mem):
                sq = seqs[i]
                ln = len(sq.prompt)
                sl = slice(cursor, cursor + ln)
                ids[si, sl] = sq.prompt
                positions[si, sl] = np.arange(ln)
                segment_ids[si, sl] = ri
                last_idx[si, ri] = cursor + ln - 1
                blk_row = np.asarray(blocks[i], np.int32)
                token_blocks[si, sl] = blk_row[np.arange(ln) // bs]
                token_offsets[si, sl] = np.arange(ln) % bs
                g = sq.gconfig
                temp[si, ri], top_k[si, ri] = g.temperature, g.top_k
                top_p[si, ri], greedy[si, ri] = g.top_p, g.greedy
                self.pos_delta[slots[i]] = 0
                cursor += ln
        toks, logps, self.cache = self._jit_prefill_rot(
            self.params, self.cache, jnp.asarray(ids),
            jnp.asarray(positions), jnp.asarray(segment_ids),
            jnp.asarray(last_idx), jnp.asarray(token_blocks),
            jnp.asarray(token_offsets), self._next_rng(),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy),
        )
        toks = np.asarray(toks)
        logps = np.asarray(logps)
        now = time.monotonic()
        for i, (seq, slot) in enumerate(zip(seqs, slots)):
            si, ri = stream_of[i]
            self._finish_prefill_bookkeeping(
                seq, slot, blocks[i], int(toks[si, ri]),
                float(logps[si, ri]), now,
            )

    def _finish_prefill_bookkeeping(
        self, seq: "_Seq", slot: int, blk_row: list[int], tok_i: int,
        logp_i: float, now: float,
    ):
        """Post-prefill slot/bookkeeping shared by the single-stream and
        rotated dispatch paths."""
        seq.slot = slot
        seq.t_first_token = now
        seq.t_last_token = now
        seq.out_tokens.append(tok_i)
        seq.out_logprobs.append(logp_i)
        seq.out_versions.append(self.version)
        self.generated_tokens_total += 1
        self.slots[slot] = seq
        # cache holds exactly the prompt tokens; the sampled token's
        # K/V is written by the next decode step
        self.cache_len[slot] = len(seq.prompt)
        self.last_token[slot] = tok_i
        self._slot_covered[slot] = list(seq.prompt)
        self.block_table[slot, : len(blk_row)] = blk_row
        self.block_table[slot, len(blk_row):] = -1
        self._slot_nblocks[slot] = len(blk_row)
        self._slot_last_use[slot] = now
        # image-conditioned rows encode pixels the token ids don't
        # show; stamp -1 so they can never be cloned into a text request
        self._slot_kv_version[slot] = -1 if seq.images else self.version
        # register the freshly prefilled prompt in the radix cache NOW, so
        # a group's queued siblings hit even while this sequence decodes
        self._cache_insert_slot(slot)
        if self._seq_finished(seq, tok_i):
            self._finish(slot, self._finish_reason(seq, tok_i))

    def _prefill_seqs(
        self, seqs: list[_Seq], slots: list[int], blocks: list[list[int]]
    ):
        """One ragged packed prefill dispatch: ANY mix of prompt lengths —
        and image prompts — share a single [Tb] segment-id stream
        (attention block-skipping keeps cost at the sum of per-prompt
        quadratics). ``blocks[i]`` are slot i's freshly allocated KV blocks
        (covering its prompt); stream-tail and dummy-row writes are routed
        to the trash block."""
        if (
            self._pp > 1
            and len(seqs) >= 2
            and not any(s.images for s in seqs)
        ):
            # pp serving: split the burst into S streams so the wavefront
            # keeps every stage busy (prefill_rotated_pp) instead of
            # dragging one stream through the sequential conveyor
            return self._prefill_seqs_rotated(seqs, slots, blocks)
        self.prefill_count += len(seqs)
        self.prefill_dispatch_count += 1
        self.prompt_tokens_total += sum(len(s.prompt) for s in seqs)
        self.prefill_tokens_computed_total += sum(len(s.prompt) for s in seqs)
        for s in seqs:
            if s.span is not None:
                s.span.event(
                    "prefill_dispatch",
                    prompt_tokens=len(s.prompt),
                    packed=len(seqs),
                )
        # compiled-shape control: the stream length buckets like prompt
        # lengths did; the segment count pads to prefill_batch (singles
        # keep a lone-row program for the common case)
        n_rows = 1 if len(seqs) == 1 else self.config.prefill_batch
        total = sum(len(s.prompt) for s in seqs)
        tb = self._stream_bucket(total)
        bs = self.block_size
        ids = np.zeros(tb, np.int32)
        positions = np.zeros(tb, np.int32)
        segment_ids = np.full(tb, -1, np.int32)
        last_idx = np.full(n_rows, tb - 1, np.int32)  # dummy rows -> pad tail
        temp = np.ones(n_rows, np.float32)
        top_k = np.zeros(n_rows, np.int32)
        top_p = np.ones(n_rows, np.float32)
        greedy = np.zeros(n_rows, bool)
        token_blocks = np.full(tb, TRASH_BLOCK, np.int32)
        token_offsets = np.zeros(tb, np.int32)
        has_images = any(s.images for s in seqs)
        mrope = has_images and self.model_config.is_qwen_vl
        pos3 = np.zeros((3, tb), np.int64) if mrope else None
        cursor = 0
        for i, s in enumerate(seqs):
            n = len(s.prompt)
            sl = slice(cursor, cursor + n)
            ids[sl] = s.prompt
            positions[sl] = np.arange(n)
            segment_ids[sl] = i
            last_idx[i] = cursor + n - 1
            blk_row = np.asarray(blocks[i], np.int32)
            token_blocks[sl] = blk_row[np.arange(n) // bs]
            token_offsets[sl] = np.arange(n) % bs
            if mrope:
                if s.grids:
                    from areal_tpu.models.vlm_qwen2 import mrope_positions

                    p3 = mrope_positions(
                        self.model_config, np.asarray(s.prompt), tuple(s.grids)
                    )
                    self.pos_delta[slots[i]] = int(p3.max() + 1 - n)
                else:
                    p3 = np.broadcast_to(np.arange(n), (3, n))
                    self.pos_delta[slots[i]] = 0
                pos3[:, sl] = p3
            else:
                self.pos_delta[slots[i]] = 0
            g = s.gconfig
            temp[i], top_k[i], top_p[i], greedy[i] = (
                g.temperature, g.top_k, g.top_p, g.greedy,
            )
            cursor += n
        args = (
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray(positions),
            jnp.asarray(segment_ids),
            jnp.asarray(last_idx),
            jnp.asarray(token_blocks),
            jnp.asarray(token_offsets),
            self._next_rng(),
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
            jnp.asarray(greedy),
        )
        if has_images:
            if mrope:
                # pixel table + grids concatenate in stream order across
                # every image-carrying prompt in the dispatch
                pixels = jnp.asarray(
                    np.concatenate(
                        [a for s in seqs if s.images for a in s.images], 0
                    ),
                    jnp.float32,
                )
                grids = tuple(g for s in seqs if s.grids for g in s.grids)
                key = ("prefill_vlm", grids, tb, n_rows)
                if key not in self._jit_cache_vlm:
                    # grids are unbounded user input (native-resolution
                    # images): bound the per-signature executable cache so
                    # a long-lived server can't grow memory monotonically
                    if len(self._jit_cache_vlm) >= 16:
                        oldest = next(iter(self._jit_cache_vlm))
                        self._jit_cache_vlm.pop(oldest)
                    self._jit_cache_vlm[key] = jax.jit(
                        functools.partial(
                            self._prefill_impl, image_grid_thw=grids
                        ),
                        donate_argnums=(1,),
                    )
                else:
                    self._jit_cache_vlm[key] = self._jit_cache_vlm.pop(key)
                toks, logps, self.cache = self._jit_cache_vlm[key](
                    *args, pixels, jnp.asarray(pos3.astype(np.int32)),
                )
            else:
                pixels = jnp.asarray(
                    np.stack(
                        [a for s in seqs if s.images for a in s.images]
                    ),
                    jnp.float32,
                )
                toks, logps, self.cache = self._jit_prefill(*args, pixels)
        else:
            toks, logps, self.cache = self._jit_prefill(*args)
        now = time.monotonic()
        toks = np.asarray(toks)
        logps = np.asarray(logps)
        for i, (seq, slot) in enumerate(zip(seqs, slots)):
            self._finish_prefill_bookkeeping(
                seq, slot, blocks[i], int(toks[i]), float(logps[i]), now
            )

    def _seq_finished(self, seq: _Seq, last_tok: int) -> bool:
        n_out = len(seq.out_tokens)
        if n_out >= seq.gconfig.max_new_tokens:
            return True
        if len(seq.prompt) + n_out >= self.config.max_seq_len:
            return True
        if n_out < seq.gconfig.min_new_tokens:
            return False
        if last_tok in seq.stop_ids(self.eos_token_id):
            return True
        return self._hit_stop_string(seq)

    def _hit_stop_string(self, seq: _Seq) -> bool:
        """Stop-string matching over the decoded tail (needs a tokenizer).
        Tokens are not trimmed back past the match; workflows that need exact
        truncation should use stop_token_ids."""
        if not seq.gconfig.stop or self.tokenizer is None:
            return False
        tail = self.tokenizer.decode(seq.out_tokens[-32:])
        return any(s in tail for s in seq.gconfig.stop)

    def _finish_reason(self, seq: _Seq, last_tok: int) -> str:
        if len(seq.out_tokens) >= seq.gconfig.min_new_tokens:
            if last_tok in seq.stop_ids(self.eos_token_id):
                return "stop"
            if self._hit_stop_string(seq):
                return "stop"
        return "length"

    def _grow_tables(self, steps: int) -> int:
        """Ensure every active slot's block table covers cache_len + steps
        tokens; under pool pressure, evict cached prefixes, then preempt the
        youngest other active sequence (abort — the client's interrupt loop
        re-issues it). Returns the table width (blocks) this chunk needs."""
        nbt = 1
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            need = min(
                self.pool.blocks_for_tokens(int(self.cache_len[i]) + steps),
                self.max_blocks_per_seq,
            )
            nbt = max(nbt, need)
            have = int(self._slot_nblocks[i])
            while need > have:
                try:
                    new = self._alloc_blocks(need - have)
                except OutOfBlocks:
                    victims = [
                        j
                        for j, q in enumerate(self.slots)
                        if q is not None and j != i
                    ]
                    if not victims:
                        # init guarantees one max-length sequence fits once
                        # caches and other actives are gone
                        raise
                    v = max(victims, key=lambda j: self.slots[j].t_submit)
                    logger.warning(
                        "KV pool exhausted: preempting rid=%s (slot %d)",
                        self.slots[v].rid, v,
                    )
                    self._finish(v, "abort", retain=False)
                    self._free_slot_blocks(v)
                    continue
                self.block_table[i, have : have + len(new)] = new
                self._slot_nblocks[i] = have + len(new)
                have += len(new)
        return nbt

    def _bucket_table_width(self, nbt: int) -> int:
        """Bucket the block-table width to powers of two: the gather view
        scales with the LONGEST live sequence, not max_seq_len, and the
        compile count stays logarithmic."""
        w = 1
        while w < nbt:
            w *= 2
        return min(w, self.max_blocks_per_seq)

    def _sampling_knobs(self):
        """Per-slot sampling knob arrays for a batched dispatch (inactive
        lanes get inert defaults)."""
        b = self.config.max_batch_size
        temp = np.ones(b, np.float32)
        top_k = np.zeros(b, np.int32)
        top_p = np.ones(b, np.float32)
        greedy = np.zeros(b, bool)
        for i, s in enumerate(self.slots):
            if s is not None:
                g = s.gconfig
                temp[i], top_k[i], top_p[i], greedy[i] = (
                    g.temperature,
                    g.top_k,
                    g.top_p,
                    g.greedy,
                )
        return temp, top_k, top_p, greedy

    def _emit_token(
        self, i: int, seq: _Seq, tok: int, logp: float, now: float
    ) -> bool:
        """Record ONE decoded token for slot ``i`` (shared by the plain
        multi-step and speculative paths): request accumulators, per-token
        version/ITL bookkeeping, covered-rows/cache_len advance. Returns
        True when the sequence finished (slot already released)."""
        seq.out_tokens.append(tok)
        seq.out_logprobs.append(logp)
        seq.out_versions.append(self.version)
        if seq.t_first_token is None:  # resumed without prefill
            seq.t_first_token = now
        if seq.t_last_token is not None:
            seq.itl.append(now - seq.t_last_token)
        seq.t_last_token = now
        self.generated_tokens_total += 1
        # the fed token's K/V row was just written at cache_len
        self._slot_covered[i].append(int(self.last_token[i]))
        self.cache_len[i] += 1
        self._slot_last_use[i] = now
        self.last_token[i] = tok
        if self._seq_finished(seq, tok):
            self._finish(i, self._finish_reason(seq, tok))
            return True
        return False

    def _propose_drafts(self):
        """Host n-gram proposals for every active slot: ``[B, K]`` draft
        tokens + per-slot valid counts. History is the slot's covered rows
        plus the pending feed token — exactly the tokens known so far.
        Slots with no match get count 0 and behave like plain one-token
        decode inside the shared verify dispatch."""
        cfg = self.config
        k = cfg.spec_draft_len
        draft = np.zeros((cfg.max_batch_size, k), np.int32)
        dlen = np.zeros(cfg.max_batch_size, np.int32)
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            # adaptive draft length: a low-acceptance sequence proposes a
            # SHORTER draft (fewer dead verify rows) while the verify
            # window's compiled width stays the static spec_draft_len —
            # unused lanes cost zeros, never a retrace
            ki = (
                (s.spec_k or self._spec_draft_max)
                if self._spec_adaptive
                else k
            )
            # slice the tail BEFORE concatenating: the proposer only
            # scans MAX_SCAN tokens, so don't copy a 32k-token list per
            # slot per window either
            cov = self._slot_covered[i]
            hist = cov[-(MAX_SCAN - 1):] + [int(self.last_token[i])]
            prop = ngram_propose(
                hist, cfg.spec_ngram_min, cfg.spec_ngram_max, ki
            )
            if prop:
                draft[i, : len(prop)] = prop
                dlen[i] = len(prop)
        return draft, dlen

    def _spec_draft_len_current(self) -> float:
        """Mean per-slot draft window over the running batch (the static
        configured length while idle or when adaptation is off)."""
        if not self._spec_enabled:
            return 0.0
        if not self._spec_adaptive:
            return float(self.config.spec_draft_len)
        ks = [
            (s.spec_k or self._spec_draft_max)
            for s in self.slots
            if s is not None
        ]
        return (
            float(sum(ks)) / len(ks) if ks else float(self._spec_draft_max)
        )

    def _spec_accept_ewma_mean(self) -> float:
        """Mean acceptance-rate EWMA over the running batch (1.0 idle —
        the optimistic prior every sequence starts from)."""
        if not self._spec_enabled:
            return 0.0
        es = [s.spec_ewma for s in self.slots if s is not None]
        return float(sum(es)) / len(es) if es else 1.0

    def _spec_adapt(self, seq: _Seq, proposed: int, accepted: int) -> None:
        """Fold one verify window's outcome into the sequence's acceptance
        EWMA and re-derive its draft window:
        ``k = min + round(ewma * (max - min))`` clamped to [min, max]."""
        alpha = self.config.spec_adapt_alpha
        rate = accepted / proposed
        seq.spec_ewma = (1.0 - alpha) * seq.spec_ewma + alpha * rate
        dmin, dmax = self._spec_draft_min, self._spec_draft_max
        seq.spec_k = min(
            dmax, max(dmin, dmin + round(seq.spec_ewma * (dmax - dmin)))
        )

    # arealint: hot-path
    def _try_spec_decode_chunk(self) -> bool:
        """One speculative window: propose drafts, verify all of them in a
        single K+1-token dispatch, emit the accepted prefix + one
        correction/bonus token, and roll back rejected tokens by NOT
        advancing ``cache_len`` past the accepted rows (free under the
        paged pool — stale rows beyond cache_len are overwritten before
        any query can attend them). Returns False to fall back to the
        plain multi-step path: no slot has an n-gram hit, or some active
        slot sits too close to max_seq_len for a full static-width window
        (the window never shrinks — that would retrace the verify program
        per residual length)."""
        k = self.config.spec_draft_len
        for i, s in enumerate(self.slots):
            if s is not None and (
                self.config.max_seq_len - int(self.cache_len[i]) < k + 1
            ):
                return False
        draft, dlen = self._propose_drafts()
        hits = int((dlen > 0).sum())
        # mixed-batch guard: a verify window emits at most 1 token for a
        # draft-less slot, so one repetitive sequence in a large diverse
        # batch must not drag everyone off the steps_per_call-amortized
        # plain path — take the window only when a meaningful fraction of
        # the batch can benefit
        if hits == 0 or hits < max(1, self.n_running // 4):
            return False
        nbt = self._bucket_table_width(self._grow_tables(k + 1))
        if self.n_running == 0:
            return True  # everything was preempted while growing tables
        active = np.array([s is not None for s in self.slots])
        # _grow_tables may have preempted slots AFTER their drafts were
        # proposed: zero those lanes' draft counts so garbage trash-block
        # logits can never count as proposals/accepts in the metrics
        dlen = np.where(active, dlen, 0).astype(np.int32)
        temp, top_k, top_p, greedy = self._sampling_knobs()
        self.decode_dispatch_count += 1
        toks, logps, n_acc, self.cache = self._jit_spec_decode(
            self.params,
            self.cache,
            jnp.asarray(self.last_token),
            jnp.asarray(draft),
            jnp.asarray(dlen),
            jnp.asarray(self.cache_len),
            jnp.asarray(self.block_table[:, :nbt]),
            jnp.asarray(active),
            self._next_rng(),
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
            jnp.asarray(greedy),
            jnp.asarray(self.pos_delta),
        )
        # intended sync: the verify window is over; sampled tokens must
        # reach python to be emitted / checked for stop conditions
        toks = np.asarray(toks)  # [B, K+1]  # arealint: disable=host-sync-in-hot-path
        logps = np.asarray(logps)  # arealint: disable=host-sync-in-hot-path
        n_acc = np.asarray(n_acc)  # arealint: disable=host-sync-in-hot-path
        self.spec_steps_total += 1
        self.spec_proposed_tokens_total += int(dlen.sum())
        self.spec_accepted_tokens_total += int(n_acc.sum())
        now = time.monotonic()
        for i, seq in enumerate(self.slots):
            if seq is None:
                continue
            if seq.span is not None:
                seq.span.event(
                    "spec_accept",
                    proposed=int(dlen[i]),
                    accepted=int(n_acc[i]),
                )
            if self._spec_adaptive and int(dlen[i]) > 0:
                self._spec_adapt(seq, int(dlen[i]), int(n_acc[i]))
            # accepted drafts then the correction/bonus token; a stop token
            # mid-window truncates — _emit_token released the slot and the
            # remaining accepted tokens are dropped (cache_len stays at the
            # last emitted row, like any other early finish)
            for t in range(int(n_acc[i]) + 1):
                if self._emit_token(
                    i, seq, int(toks[i, t]), float(logps[i, t]), now
                ):
                    break
        return True

    # arealint: hot-path
    def _decode_chunk(self):
        if self._spec_enabled and self._try_spec_decode_chunk():
            return
        # never decode past any active slot's cache capacity
        steps = self.config.decode_steps_per_call
        for i, s in enumerate(self.slots):
            if s is not None:
                steps = min(steps, self.config.max_seq_len - int(self.cache_len[i]))
        steps = max(steps, 1)
        nbt = self._grow_tables(steps)
        if self.n_running == 0:
            return  # everything was preempted while growing tables
        active = np.array([s is not None for s in self.slots])
        nbt = self._bucket_table_width(nbt)
        temp, top_k, top_p, greedy = self._sampling_knobs()
        self.decode_dispatch_count += 1
        toks, logps, self.cache = self._jit_decode(
            self.params,
            self.cache,
            jnp.asarray(self.last_token),
            jnp.asarray(self.cache_len),
            jnp.asarray(self.block_table[:, :nbt]),
            jnp.asarray(active),
            self._next_rng(),
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
            jnp.asarray(greedy),
            jnp.asarray(self.pos_delta),
            steps=steps,
        )
        # intended sync: one pull per steps_per_call-token window (already
        # amortized); tokens must reach python to be emitted
        toks = np.asarray(toks)  # [steps, B]  # arealint: disable=host-sync-in-hot-path
        logps = np.asarray(logps)  # arealint: disable=host-sync-in-hot-path
        now = time.monotonic()
        for i, seq in enumerate(self.slots):
            if seq is None:
                continue
            if seq.span is not None:
                seq.span.event("decode_segment", steps=int(toks.shape[0]))
            for t in range(toks.shape[0]):
                if self._emit_token(
                    i, seq, int(toks[t, i]), float(logps[t, i]), now
                ):
                    break

    def _finish(
        self, slot: int, reason: str, retain: bool = False, pin: bool = False
    ):
        seq = self.slots[slot]
        if seq is None:
            return
        self.slots[slot] = None
        if seq.prefill_only and seq.out_tokens:
            # disaggregated prefill leg: the whole point of this request
            # is the KV it leaves behind — retain AND pin unconditionally
            # so export_kv finds it (release_kv / ship drops the pin)
            retain = pin = True
        if retain and (seq.out_tokens or pin):
            self._retain_seq(slot, seq, pin=pin)
        # keep cache_len, covered tokens, and the block table — the rows
        # stay valid as prefix-clone sources until the pool reclaims them
        # (inactive lanes write to the trash block, so a full table poses
        # no idle-write hazard). The radix cache additionally registers the
        # FULL covered blocks (prompt + generated tokens — the multi-turn
        # reuse case) and the admission pins drop so LRU eviction can
        # reclaim the nodes once idle.
        self._cache_insert_slot(slot)
        self._unpin_slot_nodes(slot)
        self._slot_last_use[slot] = time.monotonic()
        seq.on_done(self._response(seq, reason))

    def _evict_retained(self, rid: str):
        with self._retained_lock:
            ent = self._retained.pop(rid, None)
            if ent is not None:
                self._retained_slots.pop(ent.slot, None)
                # rows stay valid (see _finish): still a prefix-clone source

    def _evict_lru_retained(self):
        """Evict ONE retained entry under pool/slot pressure, by preference
        ladder: unpinned-and-idle first, then unpinned-but-queued (forces
        the full re-prefill retention exists to avoid), then pinned
        (interrupt/preempt continuations) as a last resort — the guarantee
        that one max-length sequence always fits outranks the pin. Within
        a rank, oldest first. Evicting a preemption victim's pinned entry
        converts the internal requeue into a client-visible interrupt (the
        client replays prompt+accumulated; correctness is preserved, only
        the zero-recompute fast path is lost)."""
        # scheduler lock is NOT held while _retained_lock is (leaf-lock
        # discipline): snapshot the pending set first
        pending = self.scheduler.pending_rids()
        with self._retained_lock:
            if not self._retained:
                return
            rid = min(
                self._retained,
                key=lambda r: (
                    2 * int(self._retained[r].pinned)
                    + int(r in pending),
                    self._retained[r].ts,
                ),
            )
            ent = self._retained.pop(rid)
            self._retained_slots.pop(ent.slot, None)
        if rid in self._preempted_rids:
            self._preempted_rids.discard(rid)
            for seq in self.scheduler.remove_rids({rid}):
                self._note_interrupt(seq, "evicted")
                seq.on_done(self._response(seq, "interrupt"))

    def _response(self, seq: _Seq, reason: str) -> ModelResponse:
        now = time.monotonic()
        # latency histograms (p50/p95/p99 via the unified registry):
        # observed once per request at finish — off the per-token path
        if seq.t_first_token is not None:
            self._ttft_hist.observe(seq.t_first_token - seq.t_submit)
            if seq.t_admitted is not None:
                # TTFT decomposition: admission -> first token is prefill
                # compute for a fresh admission, but pure decode for a
                # zero-re-prefill resume (the prefill cost was paid — and
                # observed — elsewhere, possibly on another server)
                self._ttft_phase_hist.labels(
                    phase=(
                        "first_decode"
                        if seq.admitted_via_resume
                        else "prefill"
                    )
                ).observe(seq.t_first_token - seq.t_admitted)
            for d in seq.itl:
                self._itl_hist.observe(d)
        return ModelResponse(
            input_tokens=list(seq.prompt),
            output_tokens=list(seq.out_tokens),
            output_logprobs=list(seq.out_logprobs),
            output_versions=list(seq.out_versions),
            stop_reason=reason,
            latency=now - seq.t_submit,
            ttft=(seq.t_first_token or now) - seq.t_submit,
            itl=list(seq.itl),
            tokenizer=self.tokenizer,
        )
